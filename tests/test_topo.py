"""Topo framework tests: dims_create, cartesian maps/shift/sub, graph and
dist-graph adjacency, treematch-style reorder, neighbor collectives — all
against numpy references on the 8-device CPU loopback mesh (SURVEY.md §4)."""

import numpy as np
import pytest

import jax.numpy as jnp

import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu import topo
from zhpe_ompi_tpu.core import errors

N = 8


@pytest.fixture(scope="module")
def world():
    return zmpi.init()


def run_spmd(comm, fn, x_global, out_specs=None):
    xs = comm.device_put_sharded(jnp.asarray(x_global))
    return np.asarray(comm.run(fn, xs, out_specs=out_specs))


class TestDimsCreate:
    def test_balanced(self):
        assert topo.dims_create(8, 3) == [2, 2, 2]
        assert topo.dims_create(12, 2) == [4, 3]
        assert topo.dims_create(7, 2) == [7, 1]

    def test_constrained(self):
        assert topo.dims_create(8, 2, [4, 0]) == [4, 2]
        assert topo.dims_create(6, 3, [0, 3, 0]) == [2, 3, 1]

    def test_errors(self):
        with pytest.raises(errors.ArgError):
            topo.dims_create(8, 2, [3, 0])  # 8 % 3 != 0
        with pytest.raises(errors.ArgError):
            topo.dims_create(8, 2, [2, 2])  # fully fixed, wrong product


class TestCart:
    def test_coords_rank_roundtrip(self, world):
        cart = topo.CartTopology(world, (4, 2), periods=(True, False))
        for r in range(N):
            assert cart.rank_of(cart.coords(r)) == r
        assert cart.coords(0) == (0, 0)
        assert cart.coords(5) == (2, 1)  # row-major
        # periodic wrap on dim 0, error on non-periodic dim 1
        assert cart.rank_of((-1, 0)) == cart.rank_of((3, 0))
        with pytest.raises(errors.RankError):
            cart.rank_of((0, 2))

    def test_shift_tables(self, world):
        cart = topo.CartTopology(world, (4, 2), periods=(True, False))
        src, dst = cart.shift(0, 1)
        # periodic ring of 4 along dim 0 at fixed col
        assert dst[cart.rank_of((3, 0))] == cart.rank_of((0, 0))
        assert src[cart.rank_of((0, 0))] == cart.rank_of((3, 0))
        src, dst = cart.shift(1, 1)
        # non-periodic: col 1 has PROC_NULL dest, col 0 PROC_NULL source
        assert dst[cart.rank_of((0, 1))] == -1
        assert src[cart.rank_of((0, 0))] == -1

    def test_shift_exchange_traced(self, world):
        cart = topo.CartTopology(world, (8,), periods=(True,))
        x = np.arange(N, dtype=np.float32).reshape(N, 1)
        out = run_spmd(world, lambda s: cart.shift_exchange(s, 0, 1), x)
        # rank r receives from r-1 (periodic)
        expect = np.roll(x, 1, axis=0)
        np.testing.assert_array_equal(out, expect)

    def test_shift_exchange_nonperiodic_boundary(self, world):
        cart = topo.CartTopology(world, (8,), periods=(False,))
        x = np.arange(1, N + 1, dtype=np.float32).reshape(N, 1)
        out = run_spmd(world, lambda s: cart.shift_exchange(s, 0, 1), x)
        expect = np.roll(x, 1, axis=0)
        expect[0] = 0.0  # MPI_PROC_NULL edge → zeros
        np.testing.assert_array_equal(out, expect)

    def test_cart_sub(self, world):
        cart = topo.CartTopology(world, (4, 2), periods=(True, False))
        rows, rtopo = cart.sub([True, False])  # keep dim 0: two col-groups
        assert rows.is_partitioned and len(rows.partition) == 2
        assert rtopo.dims == (4,) and rtopo.periods == (True,)
        # each group contains the 4 ranks of one column, row-major order
        cols = sorted(tuple(g.ranks) for g in rows.partition)
        assert cols == [
            tuple(cart.rank_of((i, 0)) for i in range(4)),
            tuple(cart.rank_of((i, 1)) for i in range(4)),
        ]

    def test_bad_dims(self, world):
        with pytest.raises(errors.CommError):
            topo.CartTopology(world, (3, 2))  # 6 != 8


class TestGraph:
    def test_index_edges(self, world):
        # ring as an MPI graph: each rank lists its two ring neighbors
        index, edges = [], []
        for r in range(N):
            edges += [(r - 1) % N, (r + 1) % N]
            index.append(len(edges))
        g = topo.GraphTopology(world, index, edges)
        assert g.neighbors_count(0) == 2
        assert g.neighbors(0) == [N - 1, 1]
        assert sorted(g.in_neighbors(0)) == [1, N - 1]

    def test_malformed(self, world):
        with pytest.raises(errors.ArgError):
            topo.GraphTopology(world, [2] + [1] * (N - 1), [0, 1])  # not monotone
        with pytest.raises(errors.ArgError):
            topo.GraphTopology(world, [1] * N, [0, 1])  # index[-1] != len(edges)

    def test_dist_graph_adjacent(self, world):
        edge_list = [(r, (r + 1) % N) for r in range(N)]
        dg = topo.DistGraphTopology.from_edges(world, edge_list)
        indeg, outdeg, weighted = dg.neighbors_count(3)
        assert (indeg, outdeg) == (1, 1)
        srcs, _, dsts, _ = dg.neighbors(3)
        assert srcs == [2] and dsts == [4]

    def test_dist_graph_inconsistent(self, world):
        with pytest.raises(errors.ArgError):
            topo.DistGraphTopology(
                world, [[1]] + [[]] * (N - 1), [[]] * N
            )


class TestReorder:
    def test_chain_placement(self):
        # traffic: 0-3 heavy, 3-1 medium, rest light — expect a chain
        t = np.zeros((4, 4))
        t[0, 3] = 10.0
        t[3, 1] = 5.0
        t[1, 2] = 1.0
        perm = topo.reorder_greedy(t)
        assert sorted(perm) == [0, 1, 2, 3]
        pos = {r: i for i, r in enumerate(perm)}
        assert abs(pos[0] - pos[3]) == 1  # heaviest pair adjacent
        assert abs(pos[3] - pos[1]) == 1


class TestNeighborColl:
    def test_cart_ring_allgather(self, world):
        cart = topo.CartTopology(world, (8,), periods=(True,))
        x = np.arange(N, dtype=np.float32).reshape(N, 1)
        from jax.sharding import PartitionSpec as P

        out = run_spmd(
            world, lambda s: topo.neighbor_allgather(cart, s), x,
            out_specs=P("world"),
        ).reshape(N, 2, 1)
        for r in range(N):
            # slot order per dim: [minus-neighbor, plus-neighbor]
            np.testing.assert_array_equal(out[r, 0], x[(r - 1) % N])
            np.testing.assert_array_equal(out[r, 1], x[(r + 1) % N])

    def test_cart_nonperiodic_boundary_zeros(self, world):
        cart = topo.CartTopology(world, (8,), periods=(False,))
        x = np.arange(1, N + 1, dtype=np.float32).reshape(N, 1)
        from jax.sharding import PartitionSpec as P

        out = run_spmd(
            world, lambda s: topo.neighbor_allgather(cart, s), x,
            out_specs=P("world"),
        ).reshape(N, 2, 1)
        assert out[0, 0, 0] == 0.0  # no minus-neighbor at the edge
        assert out[N - 1, 1, 0] == 0.0
        np.testing.assert_array_equal(out[1, 0], x[0])

    def test_cart_2d_alltoall(self, world):
        cart = topo.CartTopology(world, (4, 2), periods=(True, True))
        # payload: block j of rank r is r*10 + j; deg = 4 (2 dims)
        x = np.zeros((N, 4, 1), dtype=np.float32)
        for r in range(N):
            for j in range(4):
                x[r, j, 0] = r * 10 + j
        from jax.sharding import PartitionSpec as P

        out = run_spmd(
            world, lambda s: topo.neighbor_alltoall(cart, s[0]), x,
            out_specs=P("world"),
        ).reshape(N, 4, 1)
        # independent model of MPI pairing: recv slot k of rank r matches
        # the occurrence-th send of src=nbrs[k] addressed to r (MPI
        # non-overtaking order; duplicates pair in order)
        for r in range(N):
            nbrs = cart.neighbor_ranks(r)
            for k, src in enumerate(nbrs):
                occurrence = nbrs[:k].count(src)
                src_out = cart.neighbor_ranks(src)
                sslot = [j for j, d in enumerate(src_out) if d == r][occurrence]
                assert out[r, k, 0] == src * 10 + sslot

    def test_graph_neighbor_allgather(self, world):
        # directed star: every rank sends to rank 0
        edge_list = [(r, 0) for r in range(1, N)]
        dg = topo.DistGraphTopology.from_edges(world, edge_list)
        x = np.arange(N, dtype=np.float32).reshape(N, 1)
        from jax.sharding import PartitionSpec as P

        out = run_spmd(
            world, lambda s: topo.neighbor_allgather(dg, s), x,
            out_specs=P("world"),
        ).reshape(N, N - 1, 1)
        # rank 0's slots hold ranks 1..7 in source order; others all zero
        np.testing.assert_array_equal(
            out[0, :, 0], np.arange(1, N, dtype=np.float32)
        )
        assert (out[1:] == 0).all()

    def test_size2_periodic_duplicate_edges(self, world):
        """dims=(2,) periodic: each rank's minus and plus neighbor are the
        same rank — duplicate edges must pair by occurrence order."""
        sub = world.split([0, 0, 1, 1, 2, 2, 3, 3])
        cart = topo.CartTopology(sub, (2,), periods=(True,))
        x = np.zeros((N, 2, 1), dtype=np.float32)
        for r in range(N):
            x[r, 0, 0] = r * 10
            x[r, 1, 0] = r * 10 + 1
        from jax.sharding import PartitionSpec as P

        out = run_spmd(
            sub, lambda s: topo.neighbor_alltoall(cart, s[0]), x,
            out_specs=P("world"),
        ).reshape(N, 2, 1)
        # within each pair (a=2k, b=2k+1): a's slot 0 gets b's block 0
        for k in range(4):
            a, b = 2 * k, 2 * k + 1
            assert out[a, 0, 0] == b * 10
            assert out[a, 1, 0] == b * 10 + 1
            assert out[b, 0, 0] == a * 10
            assert out[b, 1, 0] == a * 10 + 1
