"""MFU lever sweep on the real chip: batch size x remat x flash for the
headline config.  Steady-state discipline from bench.py (burn-in window,
median of 3).

Run from repo root: python benchmarks/mfu_sweep.py
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu.models import transformer as tfm

    import bench

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs[:1]).reshape(1, 1), ("dp", "tp"))
    dp_comm = zmpi.Communicator(mesh, "dp", name="sweep_dp")

    peak, _ = bench._chip_peak(devs[0])

    for batch, remat, seq in [
        (8, False, 512), (16, False, 512), (32, False, 512),
        (16, True, 512), (32, True, 512), (64, True, 512),
    ]:
        cfg = tfm.Config(
            vocab=8192, d_model=1024, n_heads=16, d_ff=4096, n_layers=4,
            seq=seq, dtype=jnp.bfloat16, remat=remat,
        )
        r = np.random.default_rng(0)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tok = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
        tgt = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
        step, specs = tfm.make_train_step(cfg, mesh, dp_comm, None)
        sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                   for k, v in params.items()}
        dspec = NamedSharding(mesh, P("dp"))
        tokd, tgtd = jax.device_put(tok, dspec), jax.device_put(tgt, dspec)
        try:
            ps, loss = step(sharded, tokd, tgtd)
            for _ in range(3):
                ps, loss = step(ps, tokd, tgtd)
            float(loss)
            iters = max(4, int(0.5 / (0.003 * batch)))
            times = []
            for w in range(4):  # first window discarded
                t0 = time.perf_counter()
                for _ in range(iters):
                    ps, loss = step(ps, tokd, tgtd)
                float(loss)
                if w > 0:
                    times.append((time.perf_counter() - t0) / iters)
            med = float(np.median(times))
            fl = bench._train_flops_per_step(cfg, batch)
            print(f"B={batch:3d} remat={int(remat)} seq={seq}: "
                  f"{med*1e3:7.2f} ms  {batch*seq/med:9.0f} tok/s  "
                  f"MFU {fl/med/peak*100:5.2f}%", flush=True)
        except Exception as e:
            print(f"B={batch:3d} remat={int(remat)} seq={seq}: FAILED "
                  f"{type(e).__name__}: {str(e)[:120]}", flush=True)


if __name__ == "__main__":
    main()
