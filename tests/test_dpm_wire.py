"""Wire-plane dynamic process management: connect/accept between
independent TcpProc groups, REAL multi-process spawn, and intercomm
collectives across the bridge (rounds out VERDICT items 1, 2, 7)."""

import threading

import numpy as np

from test_tcp import run_tcp
from zhpe_ompi_tpu import ops as zops
from zhpe_ompi_tpu.coll.inter import PROC_NULL, ROOT
from zhpe_ompi_tpu.comm import dpm_wire
from zhpe_ompi_tpu.pt2pt.tcp import TcpProc


def run_two_groups(na, nb, fa, fb, timeout=60.0):
    """Launch two independent TcpProc groups in threads; group A rank 0
    opens a port whose name group B uses to connect."""
    port = dpm_wire.open_port()
    results = {"a": [None] * na, "b": [None] * nb}
    excs = []

    def make_group(n, fn, tagname, store):
        coord_ready = threading.Event()
        coord_addr = [None]

        def publish(addr):
            coord_addr[0] = addr
            coord_ready.set()

        def main(rank):
            try:
                if rank == 0:
                    proc = TcpProc(0, n, coordinator=("127.0.0.1", 0),
                                   on_coordinator_bound=publish)
                else:
                    coord_ready.wait(10)
                    proc = TcpProc(rank, n, coordinator=coord_addr[0])
                try:
                    store[rank] = fn(proc)
                finally:
                    proc.close()
            except BaseException as e:  # noqa: BLE001
                excs.append(e)
                coord_ready.set()

        return [threading.Thread(target=main, args=(r,)) for r in range(n)]

    threads = (make_group(na, lambda p: fa(p, port), "a", results["a"])
               + make_group(nb, lambda p: fb(p, port.name), "b",
                            results["b"]))
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "dpm group rank hung"
    port.close()
    if excs:
        raise excs[0]
    return results


class TestConnectAccept:
    def test_bridge_pt2pt(self):
        """Ranks of two independent groups exchange across the bridge."""

        def side_a(p, port):
            ic = dpm_wire.accept(port if p.rank == 0 else None, p)
            ic.send(("from-a", p.rank), dest=p.rank, tag=3)
            got = ic.recv(source=p.rank, tag=4)
            ic.barrier()
            return got

        def side_b(p, name):
            ic = dpm_wire.connect(name, p)
            got = ic.recv(source=p.rank, tag=3)
            ic.send(("from-b", p.rank), dest=p.rank, tag=4)
            ic.barrier()
            return got

        res = run_two_groups(2, 2, side_a, side_b)
        assert res["a"] == [("from-b", 0), ("from-b", 1)]
        assert res["b"] == [("from-a", 0), ("from-a", 1)]

    def test_asymmetric_group_sizes(self):
        def side_a(p, port):
            ic = dpm_wire.accept(port if p.rank == 0 else None, p)
            assert ic.remote_size == 3
            # gather one value from every remote rank
            vals = sorted(ic.recv(source=r, tag=9) for r in range(3))
            ic.barrier()
            return vals

        def side_b(p, name):
            ic = dpm_wire.connect(name, p)
            assert ic.remote_size == 1
            ic.send(p.rank * 5, dest=0, tag=9)
            ic.barrier()
            return True

        res = run_two_groups(1, 3, side_a, side_b)
        assert res["a"][0] == [0, 5, 10]


class TestIntercommCollectives:
    def test_bcast_allreduce_allgather_barrier(self):
        """The VERDICT item-2 acceptance set, over a wire bridge."""

        def side_a(p, port):
            ic = dpm_wire.accept(port if p.rank == 0 else None, p)
            # bcast rooted in group A rank 1
            root = ROOT if p.rank == 1 else PROC_NULL
            ic.bcast({"cfg": 42} if p.rank == 1 else None, root=root)
            # allreduce: we receive the REMOTE group's sum
            their_sum = ic.allreduce(p.rank + 1, zops.SUM)
            # allgather: remote group's values
            theirs = ic.allgather(f"a{p.rank}")
            ic.barrier()
            return (their_sum, theirs)

        def side_b(p, name):
            ic = dpm_wire.connect(name, p)
            got = ic.bcast(None, root=1)  # root is rank 1 of remote group
            their_sum = ic.allreduce(10 * (p.rank + 1), zops.SUM)
            theirs = ic.allgather(f"b{p.rank}")
            ic.barrier()
            return (got, their_sum, theirs)

        res = run_two_groups(2, 3, side_a, side_b)
        # A received B's sum: 10+20+30
        for r in range(2):
            assert res["a"][r] == (60, ["b0", "b1", "b2"])
        for r in range(3):
            assert res["b"][r] == ({"cfg": 42}, 1 + 2, ["a0", "a1"])

    def test_rooted_reduce_gather_scatter(self):
        def side_a(p, port):
            ic = dpm_wire.accept(port if p.rank == 0 else None, p)
            root = ROOT if p.rank == 0 else PROC_NULL
            red = ic.reduce(None, zops.MAX, root=root)
            gat = ic.gather(root=root)
            ic.scatter([100, 200] if p.rank == 0 else None,
                       root=ROOT if p.rank == 0 else PROC_NULL)
            ic.barrier()
            return (red, gat)

        def side_b(p, name):
            ic = dpm_wire.connect(name, p)
            ic.reduce((p.rank + 1) * 7, zops.MAX, root=0)
            ic.gather(f"v{p.rank}", root=0)
            block = ic.scatter(root=0)
            ic.barrier()
            return block

        res = run_two_groups(1, 2, side_a, side_b)
        assert res["a"][0] == (14, ["v0", "v1"])
        assert res["b"] == [100, 200]


class TestThreadIntercommCollectives:
    def test_spawn_collectives(self):
        """Thread-plane dpm spawn: the same collective set crosses the
        parent/child bridge (VERDICT item-2 done criterion)."""
        from zhpe_ompi_tpu.comm import dpm
        from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse

        uni = LocalUniverse(2)

        def child_main(ctx):
            parent = dpm.get_parent(ctx)
            got = parent.bcast(None, root=0)
            s = parent.allreduce((ctx.rank + 1) * 10, zops.SUM)
            vals = parent.allgather(f"c{ctx.rank}")
            parent.barrier()
            return (got, s, vals)

        def main(ctx):
            ic, handle = dpm.spawn(uni, ctx, child_main, n_children=3)
            root = ROOT if ctx.rank == 0 else PROC_NULL
            ic.bcast("hello" if ctx.rank == 0 else None, root=root)
            s = ic.allreduce(ctx.rank + 1, zops.SUM)
            vals = ic.allgather(f"p{ctx.rank}")
            ic.barrier()
            child_results = handle.join() if ctx.rank == 0 else None
            return (s, vals, child_results)

        res = uni.run(main)
        for r in range(2):
            assert res[r][0] == 10 + 20 + 30  # children's sum
            assert res[r][1] == ["c0", "c1", "c2"]
        for got, s, vals in res[0][2]:
            assert got == "hello"
            assert s == 1 + 2
            assert vals == ["p0", "p1"]


def _spawned_child(proc, parent):
    """Module-level target: dpm_wire.spawn defaults to method='spawn'
    (fresh interpreters, picklable target) so a JAX-initialized parent is
    never forked (round-3 weak #3)."""
    total = proc.allreduce(proc.rank + 1, zops.SUM)
    got = parent.bcast(None, root=0)
    parent.send((proc.rank, total, got), dest=0, tag=11)
    parent.barrier()


class TestProcessSpawn:
    def test_real_process_spawn(self):
        """MPI_Comm_spawn over genuine OS processes: children live in
        their own interpreters, wire into their own universe, and speak
        to the parent over the intercomm (VERDICT Missing #7)."""
        child = _spawned_child

        def main(p):
            ic, handle = dpm_wire.spawn(p, child, n_children=2)
            root = ROOT if p.rank == 0 else PROC_NULL
            ic.bcast("cfg" if p.rank == 0 else None, root=root)
            reports = None
            if p.rank == 0:
                reports = sorted(ic.recv(source=r, tag=11)
                                 for r in range(2))
            ic.barrier()
            if p.rank == 0:
                handle.join()
            return reports

        res = run_tcp(2, main, timeout=90.0)
        assert res[0] == [(0, 3, "cfg"), (1, 3, "cfg")]


class TestGetParentIdentity:
    def test_get_parent_returns_same_comm(self):
        """MPI contract: Comm_get_parent is THE parent communicator —
        repeated calls must not reset collective sequence tags (regression:
        a fresh handle per call deadlocked the second collective)."""
        from zhpe_ompi_tpu.comm import dpm
        from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse

        uni = LocalUniverse(2)

        def child_main(ctx):
            p1 = dpm.get_parent(ctx)
            p2 = dpm.get_parent(ctx)
            assert p1 is p2
            p1.barrier()
            dpm.get_parent(ctx).barrier()  # second collective, new lookup
            return True

        def main(ctx):
            ic, handle = dpm.spawn(uni, ctx, child_main, n_children=2)
            ic.barrier()
            ic.barrier()
            return handle.join() if ctx.rank == 0 else None

        res = uni.run(main)
        assert res[0] == [True, True]
