/* dtype2_c.c — round-5 datatype tier-2 acceptance: hvector, hindexed,
 * struct, resized, subarray, darray, dup, true extent, envelope/
 * contents, deprecated MPI-1 forms.  Every constructor is exercised
 * over the wire (0 -> 1 exchange) so the typemaps are proven by
 * delivery, not just by extent queries.  Reference shapes:
 * ompi/mpi/c/{type_create_hvector,type_create_struct,
 * type_create_resized,type_create_subarray,type_create_darray,
 * type_dup,type_get_envelope}.c.  Run with >= 2 ranks. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "zompi_mpi.h"

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);  \
      MPI_Abort(MPI_COMM_WORLD, 2);                                    \
    }                                                                  \
  } while (0)

struct particle {
  double pos[3];
  int id;
  char tag;
  /* trailing padding makes sizeof > packed size */
};

int main(int argc, char **argv) {
  CHECK(MPI_Init(&argc, &argv) == MPI_SUCCESS);
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  CHECK(size >= 2);

  /* ---- struct: the heterogeneous constructor ---- */
  MPI_Datatype ptype;
  {
    int bl[3] = {3, 1, 1};
    MPI_Aint disp[3];
    struct particle probe;
    MPI_Aint base, a;
    MPI_Get_address(&probe, &base);
    MPI_Get_address(&probe.pos[0], &a);
    disp[0] = a - base;
    MPI_Get_address(&probe.id, &a);
    disp[1] = a - base;
    MPI_Get_address(&probe.tag, &a);
    disp[2] = a - base;
    MPI_Datatype types[3] = {MPI_DOUBLE, MPI_INT, MPI_CHAR};
    CHECK(MPI_Type_create_struct(3, bl, disp, types, &ptype) ==
          MPI_SUCCESS);
    /* resize to sizeof so arrays of particles stride correctly */
    MPI_Datatype raw = ptype;
    CHECK(MPI_Type_create_resized(raw, 0, sizeof(struct particle),
                                  &ptype) == MPI_SUCCESS);
    MPI_Type_free(&raw);
    CHECK(MPI_Type_commit(&ptype) == MPI_SUCCESS);
    long lb = -1, ext = -1;
    CHECK(MPI_Type_get_extent(ptype, &lb, &ext) == MPI_SUCCESS);
    CHECK(lb == 0 && ext == (long)sizeof(struct particle));
    int tsz = -1;
    CHECK(MPI_Type_size(ptype, &tsz) == MPI_SUCCESS);
    CHECK(tsz == 3 * 8 + 4 + 1); /* packed payload only */
  }
  if (rank == 0) {
    struct particle ps[4];
    memset(ps, 0, sizeof ps);
    for (int i = 0; i < 4; i++) {
      ps[i].pos[0] = i + 0.5;
      ps[i].pos[1] = i + 0.25;
      ps[i].pos[2] = i + 0.125;
      ps[i].id = 100 + i;
      ps[i].tag = (char)('a' + i);
    }
    CHECK(MPI_Send(ps, 4, ptype, 1, 1, MPI_COMM_WORLD) == MPI_SUCCESS);
  } else if (rank == 1) {
    struct particle ps[4];
    memset(ps, 0x77, sizeof ps);
    MPI_Status st;
    CHECK(MPI_Recv(ps, 4, ptype, 0, 1, MPI_COMM_WORLD, &st) ==
          MPI_SUCCESS);
    int cnt = -1;
    CHECK(MPI_Get_count(&st, ptype, &cnt) == MPI_SUCCESS && cnt == 4);
    for (int i = 0; i < 4; i++) {
      CHECK(ps[i].pos[0] == i + 0.5 && ps[i].pos[2] == i + 0.125);
      CHECK(ps[i].id == 100 + i && ps[i].tag == (char)('a' + i));
    }
  }

  /* ---- envelope/contents on the struct's resized wrapper ---- */
  {
    int ni = -1, na = -1, nd = -1, comb = -1;
    CHECK(MPI_Type_get_envelope(ptype, &ni, &na, &nd, &comb) ==
          MPI_SUCCESS);
    CHECK(comb == MPI_COMBINER_RESIZED && ni == 0 && na == 2 && nd == 1);
    MPI_Aint aints[2];
    MPI_Datatype dts[1];
    CHECK(MPI_Type_get_contents(ptype, 0, 2, 1, NULL, aints, dts) ==
          MPI_SUCCESS);
    CHECK(aints[0] == 0 && aints[1] == (MPI_Aint)sizeof(struct particle));
  }

  /* ---- hvector: byte-strided columns ---- */
  {
    MPI_Datatype col;
    /* 3 doubles strided 32 bytes apart (a column of a 4-double row) */
    CHECK(MPI_Type_create_hvector(3, 1, 32, MPI_DOUBLE, &col) ==
          MPI_SUCCESS);
    CHECK(MPI_Type_commit(&col) == MPI_SUCCESS);
    MPI_Aint tlb = -1, text = -1;
    CHECK(MPI_Type_get_true_extent(col, &tlb, &text) == MPI_SUCCESS);
    CHECK(tlb == 0 && text == 2 * 32 + 8);
    if (rank == 0) {
      double m[12];
      for (int i = 0; i < 12; i++) m[i] = i;
      CHECK(MPI_Send(m, 1, col, 1, 2, MPI_COMM_WORLD) == MPI_SUCCESS);
    } else if (rank == 1) {
      double m[12];
      for (int i = 0; i < 12; i++) m[i] = -1;
      CHECK(MPI_Recv(m, 1, col, 0, 2, MPI_COMM_WORLD,
                     MPI_STATUS_IGNORE) == MPI_SUCCESS);
      CHECK(m[0] == 0 && m[4] == 4 && m[8] == 8);
      CHECK(m[1] == -1 && m[5] == -1); /* gaps untouched */
    }
    MPI_Type_free(&col);
  }

  /* ---- subarray: interior 2x2 of a 4x4, C order ---- */
  {
    int sizes[2] = {4, 4}, subs[2] = {2, 2}, starts[2] = {1, 1};
    MPI_Datatype sub;
    CHECK(MPI_Type_create_subarray(2, sizes, subs, starts, MPI_ORDER_C,
                                   MPI_INT, &sub) == MPI_SUCCESS);
    CHECK(MPI_Type_commit(&sub) == MPI_SUCCESS);
    long lb = -1, ext = -1;
    CHECK(MPI_Type_get_extent(sub, &lb, &ext) == MPI_SUCCESS);
    CHECK(lb == 0 && ext == 16 * 4); /* full array extent */
    if (rank == 0) {
      int m[16];
      for (int i = 0; i < 16; i++) m[i] = i;
      CHECK(MPI_Send(m, 1, sub, 1, 3, MPI_COMM_WORLD) == MPI_SUCCESS);
    } else if (rank == 1) {
      int m[16];
      for (int i = 0; i < 16; i++) m[i] = -1;
      CHECK(MPI_Recv(m, 1, sub, 0, 3, MPI_COMM_WORLD,
                     MPI_STATUS_IGNORE) == MPI_SUCCESS);
      CHECK(m[5] == 5 && m[6] == 6 && m[9] == 9 && m[10] == 10);
      CHECK(m[0] == -1 && m[15] == -1);
    }
    MPI_Type_free(&sub);
  }

  /* ---- darray: 1-D block over 2 procs, then cyclic(1) ---- */
  if (rank < 2) {
    int gs[1] = {8}, dist[1] = {MPI_DISTRIBUTE_BLOCK};
    int darg[1] = {MPI_DISTRIBUTE_DFLT_DARG}, ps[1] = {2};
    MPI_Datatype da;
    CHECK(MPI_Type_create_darray(2, rank, 1, gs, dist, darg, ps,
                                 MPI_ORDER_C, MPI_INT, &da) ==
          MPI_SUCCESS);
    MPI_Aint tlb = -1, text = -1;
    CHECK(MPI_Type_get_true_extent(da, &tlb, &text) == MPI_SUCCESS);
    CHECK(tlb == (rank == 0 ? 0 : 16) && text == 16); /* 4 ints each */
    MPI_Type_free(&da);

    dist[0] = MPI_DISTRIBUTE_CYCLIC;
    CHECK(MPI_Type_create_darray(2, rank, 1, gs, dist, darg, ps,
                                 MPI_ORDER_C, MPI_INT, &da) ==
          MPI_SUCCESS);
    int tsz = -1;
    CHECK(MPI_Type_size(da, &tsz) == MPI_SUCCESS && tsz == 16);
    MPI_Aint tlb2 = -1;
    CHECK(MPI_Type_get_true_extent(da, &tlb2, &text) == MPI_SUCCESS);
    CHECK(tlb2 == (rank == 0 ? 0 : 4)); /* first owned element */
    MPI_Type_free(&da);
  }

  /* ---- dup + deprecated forms ---- */
  {
    MPI_Datatype d2;
    CHECK(MPI_Type_dup(ptype, &d2) == MPI_SUCCESS);
    int ni, na, nd, comb;
    CHECK(MPI_Type_get_envelope(d2, &ni, &na, &nd, &comb) ==
          MPI_SUCCESS && comb == MPI_COMBINER_DUP);
    int s1 = -1, s2 = -1;
    CHECK(MPI_Type_size(ptype, &s1) == MPI_SUCCESS);
    CHECK(MPI_Type_size(d2, &s2) == MPI_SUCCESS && s1 == s2);
    MPI_Type_free(&d2);

    MPI_Aint ub = -1;
    CHECK(MPI_Type_ub(ptype, &ub) == MPI_SUCCESS);
    CHECK(ub == (MPI_Aint)sizeof(struct particle));
    MPI_Aint disp2[2] = {8, 0};
    int bl2[2] = {1, 1};
    MPI_Datatype types2[2] = {MPI_INT, MPI_INT}, legacy;
    CHECK(MPI_Type_struct(2, bl2, disp2, types2, &legacy) ==
          MPI_SUCCESS);
    CHECK(MPI_Type_commit(&legacy) == MPI_SUCCESS);
    MPI_Type_free(&legacy);
  }
  MPI_Type_free(&ptype);

  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("dtype2_c OK on %d ranks\n", size);
  MPI_Finalize();
  return 0;
}
