"""Device mesh construction — the wire-up plane.

TPU-native replacement for the reference's runtime wire-up
(``ompi_rte_init`` → PMIx modex, ``ompi/runtime/ompi_mpi_init.c:508,667-700``):
on TPU there is no endpoint-address exchange to do — process identity and the
device topology come from ``jax.distributed`` + the platform, and the "modex"
is mesh construction.  ``jax.sharding.Mesh`` over ICI is the analog of the
btl/ofi endpoint set; host-loopback CPU devices are the btl/self+sm analog
(SURVEY.md §5 "Distributed communication backend").
"""

from __future__ import annotations

import contextlib
import os
import threading
import weakref

import numpy as np

import jax
from jax.sharding import Mesh

from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from ..runtime import flightrec, spc, ztrace
from ..utils import deadline as deadline_mod

_stream = mca_output.open_stream("rte")

mca_var.register(
    "rte_distributed_init",
    False,
    "Call jax.distributed.initialize() at init (multi-host/multi-process "
    "deployments; the PMIx-client analog)",
    type=bool,
)

# -- device liveness probe (opt-in device_probe_* family) -------------------

mca_var.register(
    "device_probe_enable", False,
    "Arm the device liveness probe around guarded device collectives: "
    "a region that outlives device_probe_deadline triggers a killable-"
    "child probe (tiny psum over the mesh, coll/tpu.PROBE_SRC); a "
    "missed probe classifies a typed cause=\"device\" fault into the "
    "job's FailureState.  Off by default — probes cost a subprocess",
    type=bool,
)
mca_var.register(
    "device_probe_timeout", 20.0,
    "Outer kill (seconds) of one device liveness probe child — the "
    "backstop around its internal watchdog deadline",
    type=float,
)
mca_var.register(
    "device_probe_deadline", 12.0,
    "Internal watchdog deadline (seconds) of the probe child (it "
    "os._exits from the inside at expiry — the structured \"deadline\" "
    "outcome), AND the guarded-region deadline that triggers a probe",
    type=float,
)
mca_var.register(
    "device_probe_grace", 2,
    "Probe rounds that may come back \"ok\" while the guarded region "
    "still blocks before the guard stops re-probing (a slow-but-alive "
    "local plane is a peer's fault to classify, never this rank's own)",
    type=int,
)


def distributed_initialize(**kwargs) -> None:
    """Multi-controller wire-up (PMIx_Init analog): join the JAX coordination
    service.  No-op if already initialized."""
    try:
        jax.distributed.initialize(**kwargs)
        mca_output.verbose(1, _stream, "jax.distributed initialized")
    except RuntimeError as e:
        if "already initialized" in str(e).lower():
            mca_output.verbose(1, _stream, "jax.distributed: %s", e)
        else:
            # real wire-up failure (bad coordinator, unreachable service):
            # failing loudly beats silently running at the wrong world size
            raise


def world_devices() -> list:
    """All addressable devices in process order — the proc table analog."""
    return list(jax.devices())


def world_mesh(axis_name: str = "world", devices=None) -> Mesh:
    """1-D mesh over every device: MPI_COMM_WORLD's footprint."""
    devs = np.asarray(devices if devices is not None else world_devices())
    return Mesh(devs, axis_names=(axis_name,))


def survivor_mesh(mesh: Mesh, failed, axis: str | None = None) -> Mesh:
    """The remesh step of the device-plane recovery pipeline: the same
    mesh minus the failed indices along ``axis`` (default: the first
    axis — the data-parallel outer loop).  The survivor mesh is what
    ``zero``/``grad``/``hybrid`` re-shard onto between shrink and
    respawn; a respawned job calls :func:`world_mesh`/:func:`make_mesh`
    again for the full-size resume."""
    axis = axis or mesh.axis_names[0]
    if axis not in mesh.axis_names:
        raise errors.ArgError(
            f"survivor_mesh: axis {axis!r} not in {mesh.axis_names}")
    k = mesh.axis_names.index(axis)
    drop = {int(r) for r in failed}
    arr = np.moveaxis(np.asarray(mesh.devices), k, 0)
    keep = [i for i in range(arr.shape[0]) if i not in drop]
    if not keep:
        raise errors.ArgError(
            f"survivor_mesh: every index of axis {axis!r} failed")
    sp = ztrace.begin(ztrace.REMESH, -1, axis=axis,
                      dropped=sorted(drop)) if ztrace.active else None
    out = Mesh(np.moveaxis(arr[keep], 0, k), axis_names=mesh.axis_names)
    if sp is not None:
        sp.end(survivors=len(keep))
    return out


def make_mesh(axis_sizes: dict[str, int], devices=None) -> Mesh:
    """N-D mesh, e.g. {'dp': 2, 'tp': 4}: the topo-framework analog
    (cartesian topologies, ``ompi/mca/topo``) expressed the TPU way.

    Uses jax's device-assignment heuristics so that, on real hardware, the
    trailing axes land on the fastest ICI dimensions.
    """
    names = tuple(axis_sizes.keys())
    shape = tuple(axis_sizes.values())
    if devices is None:
        try:
            return jax.make_mesh(shape, names)
        except (ValueError, RuntimeError):
            devices = world_devices()
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names=names)


# -- device liveness probe (the fault loop's device half) -------------------


def probe_device_plane(timeout: float | None = None,
                       deadline: float | None = None,
                       env: dict | None = None,
                       rank: int | None = None) -> tuple[str, str]:
    """One killable-child device liveness probe: the tiny deadline-
    bounded psum (``coll/tpu.PROBE_SRC``) through the shared
    ``utils/deadline`` idiom — exactly the machinery ``bench.py`` uses
    for its backend probe, so a wedged ``jax.devices()`` OR a wedged
    collective dies from the inside at the child's internal watchdog.

    Returns the structured ``(kind, detail)``: "ok" (detail = device
    JSON), "hung", "deadline", "error".  Counts ``device_probe_rounds``
    (and ``device_probe_misses`` on hung/deadline) and records the
    DEVICE_PROBE ztrace span, so an OSU ``--plane device`` row and a
    postmortem timeline both see every round."""
    from ..coll import tpu as coll_tpu

    timeout = float(mca_var.get("device_probe_timeout", 20.0)) \
        if timeout is None else float(timeout)
    deadline = float(mca_var.get("device_probe_deadline", 12.0)) \
        if deadline is None else float(deadline)
    if rank is not None:
        # scope the wedge-injection hook: the child wedges only when
        # the hook names THIS rank (or "1" = the whole process) — a
        # healthy rank sharing the process must get a healthy answer
        env = dict(os.environ if env is None else env)
        env[coll_tpu.PROBE_RANK_ENV] = str(int(rank))
    spc.record("device_probe_rounds")
    sp = ztrace.begin(ztrace.DEVICE_PROBE, -1) if ztrace.active else None
    kind, detail = deadline_mod.run_probe(
        coll_tpu.PROBE_SRC, timeout, deadline, env=env)
    if kind in ("hung", "deadline"):
        spc.record("device_probe_misses")
    if sp is not None:
        sp.end(kind=kind)
    return kind, detail


class DeviceLivenessProbe:
    """The armed guard: a deadline around a device-collective region,
    feeding missed probes into the SAME :class:`~zhpe_ompi_tpu.ft.ulfm.
    FailureState` the host-plane detectors feed — the device half of
    the fault loop.

    Usage (the models/ftloop shape)::

        probe = DeviceLivenessProbe(state=proc.ft_state, rank=proc.rank,
                                    on_fault=proc.flood_device_fault)
        ...
        with probe.guard():
            loss = step(params, batch)   # may wedge mid-psum

    A region that outlives ``device_probe_deadline`` triggers one
    killable-child probe from the watchdog thread (the region itself
    cannot be killed — the XLA dispatch holds the caller's thread):

    - probe MISSED ("hung"/"deadline"): the local device plane is
      wedged — classify a typed ``cause="device"`` fault for THIS rank
      into the FailureState (flooding notices exactly like transport
      deaths do, via ``on_fault``), count ``device_faults``, record the
      DEVICE_FAULT flightrec event.
    - probe OK: the local plane answers — the region is slow, or a
      REMOTE participant wedged (that rank's own guard classifies it;
      its notice unwinds us).  Re-arm, up to ``device_probe_grace``
      ok-rounds, then stop probing and leave the wait to the host
      plane.

    ``probe_fn`` is injectable (tests drill the ladder without paying
    a subprocess per case); the default is :func:`probe_device_plane`.
    ``guard()`` is a no-op unless ``device_probe_enable`` is on or the
    probe was constructed with ``enable=True`` — opt-in, per contract.
    """

    def __init__(self, state=None, rank: int = -1, on_fault=None,
                 probe_fn=None, enable: bool | None = None,
                 timeout: float | None = None,
                 deadline: float | None = None,
                 grace: int | None = None):
        self.state = state
        self.rank = int(rank)
        self.on_fault = on_fault
        self.probe_fn = probe_fn  # None = probe_device_plane, rank-scoped
        self.enabled = bool(mca_var.get("device_probe_enable", False)) \
            if enable is None else bool(enable)
        self.timeout = timeout
        self.deadline = float(mca_var.get("device_probe_deadline", 12.0)) \
            if deadline is None else float(deadline)
        self.grace = int(mca_var.get("device_probe_grace", 2)) \
            if grace is None else int(grace)
        self.fault: errors.DeviceFault | None = None

    # -- classification ----------------------------------------------------

    def classify(self, kind: str, detail: str) -> errors.DeviceFault:
        """A missed probe becomes a typed device fault: counted,
        flight-recorded, marked into the FailureState (cause="device" —
        never a detector suspicion, so the zero-false-positive gate
        keeps its meaning), and handed to ``on_fault`` (the wire
        plane's notice flood / the test's wedge release)."""
        fault = errors.DeviceFault(
            f"device plane missed its liveness deadline ({kind}: "
            f"{detail})",
            failed_ranks=[self.rank] if self.rank >= 0 else (),
            kind=kind,
        )
        spc.record("device_faults")
        flightrec.record(flightrec.DEVICE_FAULT, rank=self.rank,
                         kind=kind)
        if ztrace.active:
            ztrace.instant(ztrace.FT_CLASS, self.rank,
                           failed=self.rank, cause="device")
        if self.state is not None and self.rank >= 0:
            self.state.mark_failed(self.rank, cause="device")
        self.fault = fault
        if self.on_fault is not None:
            self.on_fault(fault)
        return fault

    def probe_once(self) -> tuple[str, str]:
        if self.probe_fn is not None:
            return self.probe_fn(timeout=self.timeout,
                                 deadline=self.deadline)
        return probe_device_plane(
            timeout=self.timeout, deadline=self.deadline,
            rank=self.rank if self.rank >= 0 else None)

    # -- the armed guard ---------------------------------------------------

    def _expired(self, watchdog) -> None:
        """Watchdog-thread body: the guarded region outlived its
        deadline.  Probe; classify a miss; tolerate up to ``grace``
        ok-rounds before going quiet (re-arming forever would turn a
        long legitimate region into a polling loop)."""
        for _ in range(max(1, self.grace)):
            kind, detail = self.probe_once()
            if watchdog._disarmed.is_set():
                return  # the region finished while we probed: no fault
            if kind in ("hung", "deadline"):
                self.classify(kind, detail)
                return
            # ok/error: the plane answered (an error is a health
            # problem, not a wedge — loud in the probe counters, not a
            # classification); wait out one more deadline
            if watchdog._disarmed.wait(self.deadline):
                return
        mca_output.verbose(
            1, _stream,
            "device probe guard: region still blocked after %d ok "
            "rounds; leaving the wait to the host plane", self.grace,
        )

    def guard(self, deadline: float | None = None):
        """Context manager arming the deadline around one device-
        collective region (one train step).  No-op when disabled."""
        if not self.enabled:
            return contextlib.nullcontext()
        wd_box: list = []
        wd = deadline_mod.Watchdog(
            float(deadline if deadline is not None else self.deadline),
            on_expire=lambda: self._expired(wd_box[0]),
            name=f"device-probe-guard-{self.rank}",
        )
        wd_box.append(wd)
        return wd


# -- the always-on background prober (the fleet-health half) ----------------

mca_var.register(
    "dvm_device_probe_interval_ms", 0,
    "Interval (milliseconds) of the ALWAYS-ON background device "
    "prober (DeviceProber): between guarded regions it runs the same "
    "killable-child liveness probe the guard runs, so a wedge that "
    "lands OUTSIDE a guarded region still classifies (cause=\"device\","
    " the typed DeviceFault path) within one interval plus one probe "
    "timeout instead of at the next collective; 0 (the default) = off",
    type=int,
)

_live_probers: weakref.WeakSet = weakref.WeakSet()


def live_prober_threads() -> list[str]:
    """Background prober threads still RUNNING — must be [] once every
    owner stopped its prober (the conftest session gate; a stopped
    prober's thread finishing one last probe call is not a leak, the
    deadline-watchdog contract)."""
    out = []
    for p in list(_live_probers):
        t = p._thread
        if t is not None and t.is_alive() and not p._stop.is_set():
            out.append(t.name)
    return out


class DeviceProber:
    """Detector-style background device prober — the always-on half of
    the device fault loop.  The :class:`DeviceLivenessProbe` guard only
    watches INSIDE guarded regions (a train step); a device plane that
    wedges between steps — data loading, checkpointing, an idle serving
    process — classifies only at the NEXT collective.  This thread
    probes on ``dvm_device_probe_interval_ms`` whenever no guarded
    region is active (:meth:`region` brackets them), feeding the same
    typed ``DeviceFault``/FailureState path via the probe's
    ``classify``, so an out-of-region wedge classifies in bounded time
    (one interval + one probe timeout).

    Counters: every background round records ``device_probes``; a miss
    records ``device_probe_faults`` (on top of the probe family's own
    ``device_probe_rounds``/``device_probe_misses``).  Hygiene:
    :func:`live_prober_threads` must be [] once owners stop — the
    models/ftloop seam starts the prober at ``run()`` entry and stops
    it on the way out."""

    def __init__(self, probe: DeviceLivenessProbe,
                 interval_ms: int | None = None):
        self.probe = probe
        ms = int(mca_var.get("dvm_device_probe_interval_ms", 0)) \
            if interval_ms is None else int(interval_ms)
        self.interval_s = ms / 1000.0
        self._stop = threading.Event()
        self._busy = 0
        self._busy_lock = threading.Lock()
        self._thread: threading.Thread | None = None
        _live_probers.add(self)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive() \
            and not self._stop.is_set()

    def start(self) -> "DeviceProber":
        """Arm the background thread; a no-op when the interval is 0
        (the opt-in gate) or the prober already runs."""
        if self.interval_s <= 0 or self.running:
            return self
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"device-prober-{self.probe.rank}",
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            with self._busy_lock:
                busy = self._busy > 0
            if busy or self.probe.fault is not None:
                # a guarded region owns this window (its watchdog
                # classifies), or a fault already classified and the
                # recovery path owns the plane until it clears
                continue
            kind, detail = self.probe.probe_once()
            spc.record("device_probes")
            if self._stop.is_set():
                return  # outcome after stop is dropped (watchdog rule)
            with self._busy_lock:
                busy = self._busy > 0
            if busy:
                continue  # a region started mid-probe: its guard owns it
            if kind in ("hung", "deadline"):
                spc.record("device_probe_faults")
                self.probe.classify(kind, detail)

    @contextlib.contextmanager
    def region(self, inner=None):
        """Bracket a guarded region (optionally entering ``inner`` —
        the probe's guard — inside the bracket): the background thread
        goes quiet while any region is active, so the two halves never
        double-probe one wedge."""
        with self._busy_lock:
            self._busy += 1
        try:
            if inner is not None:
                with inner:
                    yield
            else:
                yield
        finally:
            with self._busy_lock:
                self._busy -= 1

    def stop(self, join_timeout: float = 1.0) -> None:
        """Stop probing.  The join is a short tidy-up (a thread still
        inside a probe subprocess is bounded by the probe's outer kill
        and its outcome is dropped) — the leak gate counts only
        running probers."""
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive() \
                and t is not threading.current_thread():
            t.join(join_timeout)
