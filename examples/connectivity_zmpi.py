"""connectivity_c.c analog (reference: examples/connectivity_c.c): verify
every pair of ranks can exchange, then report.

The reference posts O(p^2) pairwise send/recvs; the SPMD equivalent
drives every pairwise path in p-1 shifted permutes (each hop distance
exercises all p source→dest pairs at that distance).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/connectivity_zmpi.py
"""

import jax.numpy as jnp
import numpy as np

import zhpe_ompi_tpu as zmpi


def main():
    comm = zmpi.init()
    n = comm.size

    def body(_):
        rank = comm.rank()
        ok = jnp.asarray(True)
        for dist in range(1, n):
            got = comm.shift(jnp.asarray(rank, jnp.int32), dist, wrap=True)
            ok = ok & (got == (rank - dist) % n)
        # all ranks must agree (LAND allreduce, as the reference gathers acks)
        return comm.allreduce(ok.astype(jnp.int32), zmpi.MIN)[None]

    out = np.asarray(comm.run(body, jnp.zeros((n, 1))))
    assert out.reshape(-1).min() == 1
    print(f"Connectivity test on {n} processes PASSED")
    zmpi.finalize()


if __name__ == "__main__":
    main()
