"""Flagship model: a transformer LM parallelized *through the framework*.

This plays the role the reference's example programs play
(``examples/ring_c.c`` etc.): a real application whose every communication
goes through the framework's communicators — the way a Megatron-style trainer
drives MPI/NCCL:

- **tp** (tensor parallel): attention heads and MLP hidden are sharded over
  the 'tp' mesh axis; partial sums after the output/down projections are
  combined with ``tp_comm.allreduce`` (the MPI_Allreduce hot path of
  BASELINE.md, executed as XLA psum on ICI).
- **dp** (data parallel): gradients are averaged with ``dp_comm.allreduce``.
- **sp** (sequence parallel / long context): ring attention over the 'sp'
  axis using ``comm.ppermute`` ring steps (see ring_attention.py).

Everything is bfloat16 on the MXU path with float32 master params/reductions,
static shapes, and scan-over-layers for compile-time O(1) in depth.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from .. import compat
from jax import lax

from .. import ops as zops


@dataclass(frozen=True)
class Config:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    d_ff: int = 512
    n_layers: int = 2
    seq: int = 64
    dtype: Any = jnp.bfloat16
    # attention impl: None = auto (Pallas flash kernel on TPU, naive jnp
    # elsewhere); True/False forces
    flash: bool | None = None
    # rematerialize layer activations in the backward pass: saves
    # O(n_layers * B * S * (D + F)) HBM for ~1/3 more forward FLOPs,
    # buying batch (and therefore MFU) at long sequence lengths.  The
    # policy keeps matmul outputs (checkpoint_dots) so only the cheap
    # elementwise/norm intermediates are recomputed.
    remat: bool = False
    # round-4 MFU levers (bench.py's cap analysis named both):
    # fused layernorm Pallas kernel: None = auto (kernel on TPU,
    # reference jnp elsewhere), True/False forces
    fused_ln: bool | None = None
    # vocab-chunked cross-entropy (no (B,S,V) materialization): chunk
    # size, or None for the unchunked reference loss
    ce_chunk: int | None = None
    # zigzag sequence parallelism: tokens arrive zigzag-sharded (rank i
    # holds global chunks (i, 2n-1-i)) and causal ring attention skips
    # the dead half of the ring work, balanced across ranks
    # (models/ring_attention.py::ring_attention_zigzag)
    zigzag_sp: bool = False


def init_params(cfg: Config, key, tp: int = 1) -> dict:
    """Initialize host-side full parameters (unsharded)."""
    k = jax.random.split(key, 8)
    D, H, F, V = cfg.d_model, cfg.d_model, cfg.d_ff, cfg.vocab
    s = lambda *shape: (cfg.n_layers,) + shape

    def nrm(kk, shape, scale):
        return (jax.random.normal(kk, shape, jnp.float32) * scale)

    return {
        "embed": nrm(k[0], (V, D), 0.02),
        # (L, D, 3, H): the q/k/v axis is explicit so tp-sharding the head
        # dim (last axis) keeps each rank's slice = q,k,v of its own heads
        "wqkv": nrm(k[1], s(D, 3, H), D**-0.5),
        "wo": nrm(k[2], s(H, D), H**-0.5),
        "w1": nrm(k[3], s(D, F), D**-0.5),
        "w2": nrm(k[4], s(F, D), F**-0.5),
        "ln1": jnp.ones(s(D)),
        "ln2": jnp.ones(s(D)),
        "lnf": jnp.ones((D,)),
    }


def _ln(x, g, fused=None):
    """Layernorm: the fused Pallas one-pass kernel on TPU (round-4 MFU
    lever), reference jnp elsewhere; numerics live in one place
    (ops/fused_norm.ln_reference)."""
    from ..ops import fused_norm

    if fused is False:
        return fused_norm.ln_reference(x, g)
    return fused_norm.layer_norm(x, g, force=fused is True)


from ..ops.flash_attention import attn_reference as _attn  # noqa: E402
# single source of attention numerics: the naive reference lives with the
# flash kernel (ops/flash_attention.py) so fallback/backward can't diverge


def forward_hidden(params: dict, tokens, cfg: Config, tp_comm=None,
                   sp_comm=None):
    """Forward pass on one device's shard, up to the final layernorm
    (pre-unembed).  See ``forward`` for the communicator semantics.

    `tp_comm` is a framework communicator over the 'tp' axis (or None for no
    tensor parallelism).  Heads and ffn-hidden arrive pre-sharded: wqkv is
    (L, D, 3, H/tp), wo is (L, H/tp, D), w1 (L, D, F/tp), w2 (L, F/tp, D).
    After wo and w2 the partial products are summed with tp_comm.allreduce —
    the framework's MPI_Allreduce on the hot path.

    `sp_comm` (sequence parallel / long context): tokens arrive sequence-
    sharded over the 'sp' axis and attention runs as ring attention over
    the framework's ppermute ring (models/ring_attention.py).
    """
    dtype = cfg.dtype
    x = params["embed"].astype(dtype)[tokens]  # (B, S_local, D)
    B, S, D = x.shape
    hd = D // cfg.n_heads
    n_heads_local = params["wqkv"].shape[-1] // hd

    from ..parallel.grad import f_identity, g_allreduce
    from .ring_attention import ring_attention

    # flash dispatch: auto picks per-platform inside flash_attention;
    # flash=True forces the kernel (interpreted off-TPU), False forces naive
    use_flash = cfg.flash is not False

    def block(x, layer):
        wqkv, wo, w1, w2, g1, g2 = layer
        h = _ln(x, g1, cfg.fused_ln)
        if tp_comm is not None:
            h = f_identity(tp_comm, h)
        qkv = jnp.einsum("bsd,dce->bsce", h, wqkv.astype(dtype))
        q = qkv[:, :, 0].reshape(B, S, n_heads_local, hd)
        k = qkv[:, :, 1].reshape(B, S, n_heads_local, hd)
        v = qkv[:, :, 2].reshape(B, S, n_heads_local, hd)
        if sp_comm is not None:
            if cfg.zigzag_sp:
                from .ring_attention import ring_attention_zigzag

                o = ring_attention_zigzag(sp_comm, q, k, v)
            else:
                o = ring_attention(sp_comm, q, k, v, causal=True)
            o = o.reshape(B, S, -1)
        elif use_flash:
            from ..ops.flash_attention import flash_attention

            o = flash_attention(
                q, k, v, causal=True, force=cfg.flash is True
            ).reshape(B, S, -1)
        else:
            o = _attn(q, k, v).reshape(B, S, -1)
        o = jnp.einsum("bse,ed->bsd", o, wo.astype(dtype))
        if tp_comm is not None:
            o = g_allreduce(tp_comm, o)
        x = x + o
        h = _ln(x, g2, cfg.fused_ln)
        if tp_comm is not None:
            h = f_identity(tp_comm, h)
        u = jnp.einsum("bsd,df->bsf", h, w1.astype(dtype))
        u = jax.nn.gelu(u)
        d = jnp.einsum("bsf,fd->bsd", u, w2.astype(dtype))
        if tp_comm is not None:
            d = g_allreduce(tp_comm, d)
        return x + d, None

    layers = (
        params["wqkv"], params["wo"], params["w1"], params["w2"],
        params["ln1"], params["ln2"],
    )
    step_fn = block
    if cfg.remat:
        step_fn = jax.checkpoint(
            block,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    x, _ = lax.scan(
        lambda carry, layer: step_fn(carry, layer), x,
        layers,
    )
    return _ln(x, params["lnf"], cfg.fused_ln)


def forward(params: dict, tokens, cfg: Config, tp_comm=None, sp_comm=None):
    """Full forward pass: hidden states -> vocabulary logits (f32)."""
    x = forward_hidden(params, tokens, cfg, tp_comm, sp_comm)
    # model-dtype operands with f32 accumulation: a full-f32 matmul here
    # runs at a fraction of MXU rate
    return jnp.einsum(
        "bsd,vd->bsv", x, params["embed"].astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    )


def loss_fn(params, tokens, targets, cfg: Config, tp_comm=None, sp_comm=None):
    """Mean token cross-entropy in the fused lse form.

    ``-logp[t] = lse(logits) - logits[t]``, with the target logit computed
    on the hidden side (``sum(x * embed[t])``) so no (B, S, V) gather or
    scatter ever materializes — the gather/scatter backward of the
    log_softmax + take_along_axis form measured 14.3 ms vs 3-5 ms for this
    form at (8, 512) x 8192 vocab on v5e.  Numerics are identical: both
    compute f32 lse and an f32 target logit from model-dtype operands.
    """
    x = forward_hidden(params, tokens, cfg, tp_comm, sp_comm)
    emb = params["embed"].astype(cfg.dtype)
    # round-4 lever: cfg.ce_chunk scans vocab chunks through the online
    # lse so no (B, S, V) f32 array ever reaches HBM; the unchunked
    # reference (ops/fused_ce.ce_reference) is this module's historical
    # loss body, bit-for-bit
    from ..ops.fused_ce import token_ce

    return token_ce(x, emb, targets, cfg.ce_chunk)


# Parameters replicated over tp (everything else is tp-sharded).
_TP_REPLICATED = frozenset({"embed", "lnf", "ln1", "ln2"})


def _param_specs(tp_ax):
    from jax.sharding import PartitionSpec as P

    return {
        "embed": P(), "lnf": P(),
        "wqkv": P(None, None, None, tp_ax),
        "wo": P(None, tp_ax, None),
        "w1": P(None, None, tp_ax),
        "w2": P(None, tp_ax, None),
        "ln1": P(), "ln2": P(),
    }


def _sync_grads(grads, loss, dp_comm, tp_comm, sp_comm, dp, tp, sp):
    """The gradient synchronization semantics (verified in tests against
    a single-device run) — ONE home for both train-step builders:
      - tp-sharded params (wqkv/wo/w1/w2): their grads are tp-local
        already; average over dp only.
      - replicated-over-tp params (embed/ln): with the f/g wrappers each
        tp rank holds the full tp-summed gradient; a tp-mean makes the
        update bitwise-identical across tp ranks.
      - sp: every rank sees only its sequence block, so EVERY param's
        grad is partial over sp — sp-mean them all (the global loss is a
        mean over tokens; dp-mean x sp-mean composes to the global mean).
    All syncs go through the framework's allreduce."""
    synced = {}
    for name, g in grads.items():
        g = dp_comm.allreduce(g, zops.SUM) / dp
        if sp_comm is not None:
            g = sp_comm.allreduce(g, zops.SUM) / sp
        if name in _TP_REPLICATED and tp_comm is not None:
            g = tp_comm.allreduce(g, zops.SUM) / tp
        synced[name] = g
    loss = dp_comm.allreduce(loss, zops.SUM) / dp
    if sp_comm is not None:
        loss = sp_comm.allreduce(loss, zops.SUM) / sp
    if tp_comm is not None:
        loss = tp_comm.allreduce(loss, zops.SUM) / tp
    return synced, loss


def make_train_step(cfg: Config, mesh, dp_comm, tp_comm, sp_comm=None,
                    lr: float = 1e-2):
    """Build the jitted SPMD training step over dp x tp (x sp): one
    fused shard_map program — grads, sync (see :func:`_sync_grads`),
    and the SGD update in a single jit (the structure bench.py's
    HLO-parity comparison against plain JAX relies on)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    dp = mesh.shape[dp_comm.axis]
    tp = mesh.shape[tp_comm.axis] if tp_comm is not None else 1
    sp = mesh.shape[sp_comm.axis] if sp_comm is not None else 1
    param_specs = _param_specs(tp_comm.axis if tp_comm is not None else None)

    def spmd_step(params, tokens, targets):
        def local_loss(p):
            return loss_fn(p, tokens, targets, cfg, tp_comm, sp_comm)

        loss, grads = jax.value_and_grad(local_loss)(params)
        synced, loss = _sync_grads(
            grads, loss, dp_comm, tp_comm, sp_comm, dp, tp, sp
        )
        new_params = jax.tree.map(
            lambda p, g: (p - lr * g).astype(p.dtype), params, synced
        )
        return new_params, loss

    sp_ax = sp_comm.axis if sp_comm is not None else None
    data_spec = P(dp_comm.axis, sp_ax)
    step = jax.jit(
        compat.shard_map(
            spmd_step,
            mesh=mesh,
            in_specs=(param_specs, data_spec, data_spec),
            out_specs=(param_specs, P()),
            check_vma=False,
        )
    )
    return step, param_specs


def make_train_step_optax(cfg: Config, mesh, dp_comm, tp_comm,
                          sp_comm=None, optimizer=None, dcn_proc=None,
                          dcn_weight: float | None = None,
                          dcn_sharded: bool = False):
    """Stateful-optimizer training step: the framework's SPMD grad
    computation composed with any optax GradientTransformation.

    The gradient pass is the same shard_map program ``make_train_step``
    builds (framework allreduces on the dp/tp/sp axes); the optimizer
    update runs in a second jit whose optimizer-state shardings follow
    from the gradient/parameter shardings by XLA propagation — Adam
    moments land sharded exactly like their parameters with no
    hand-written state specs.

    ``dcn_proc``: a host-plane endpoint (TcpProc from ``host_init``)
    makes this a MULTI-SLICE step — the in-mesh-synced gradients are
    additionally allreduce-meaned across launcher slices
    (:func:`zhpe_ompi_tpu.parallel.hybrid.dcn_grad_sync`) between the
    two jits, the ICI-inside/DCN-outside composition.  The loss scalar
    rides the same bucketed sync (no extra per-step DCN round trip).
    ``dcn_weight``: this slice's fraction of the global batch when
    slices carry unequal batches (default: equal, 1/size).

    Returns ``(init_opt_state, step, param_specs)``: ``step(params,
    opt_state, tokens, targets) -> (params, opt_state, loss)``."""
    import optax

    if optimizer is None:
        optimizer = optax.adam(1e-3)

    from jax.sharding import PartitionSpec as P

    dp = mesh.shape[dp_comm.axis]
    tp = mesh.shape[tp_comm.axis] if tp_comm is not None else 1
    sp = mesh.shape[sp_comm.axis] if sp_comm is not None else 1
    param_specs = _param_specs(tp_comm.axis if tp_comm is not None else None)

    def spmd_grads(params, tokens, targets):
        def local_loss(p):
            return loss_fn(p, tokens, targets, cfg, tp_comm, sp_comm)

        loss, grads = jax.value_and_grad(local_loss)(params)
        return _sync_grads(
            grads, loss, dp_comm, tp_comm, sp_comm, dp, tp, sp
        )

    sp_ax = sp_comm.axis if sp_comm is not None else None
    data_spec = P(dp_comm.axis, sp_ax)
    grad_step = jax.jit(
        compat.shard_map(
            spmd_grads, mesh=mesh,
            in_specs=(param_specs, data_spec, data_spec),
            out_specs=(param_specs, P()),
            check_vma=False,
        )
    )

    init_opt_state = jax.jit(optimizer.init)

    def _apply(params, opt_state, grads):
        updates, opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # preserve storage dtype (apply_updates upcasts mixed dtypes)
        new_params = jax.tree.map(
            lambda new, old: new.astype(old.dtype), new_params, params
        )
        return new_params, opt_state

    # donate the old params + optimizer state: callers thread both
    # through step() and never reuse them, so the update is in-place at
    # the XLA level instead of holding 2x params + both moment trees
    apply = jax.jit(_apply, donate_argnums=(0, 1))

    from jax.sharding import NamedSharding

    grad_shardings = {
        k: NamedSharding(mesh, spec) for k, spec in param_specs.items()
    }

    def step(params, opt_state, tokens, targets):
        grads, loss = grad_step(params, tokens, targets)
        if dcn_proc is not None and dcn_proc.size > 1:
            from ..parallel import hybrid

            if dcn_sharded:
                # scaling path (round 4): each distinct device shard
                # syncs with its same-index peer across slices — host
                # memory and DCN traffic are O(unique shard bytes),
                # shardings preserved with no reshard (identical meshes
                # on every slice, fingerprint-enforced).  The loss
                # scalar rides the same call's host-leaf bucket — no
                # extra DCN round trip.
                bundle = hybrid.dcn_grad_sync_sharded(
                    dcn_proc,
                    {"grads": grads,
                     "loss": np.asarray(loss, np.float32)},
                    weight=dcn_weight)
                grads = bundle["grads"]
                loss = jnp.asarray(bundle["loss"])
            else:
                # small-slice default: pack_tree gathers each gradient
                # fully to numpy and one bucketed allreduce syncs it —
                # fewer, larger messages, at the cost of full-tensor
                # host replication per step
                bundle = hybrid.dcn_grad_sync(
                    dcn_proc,
                    {"grads": grads,
                     "loss": np.asarray(loss, np.float32)},
                    weight=dcn_weight,
                )
                # Re-shard the synced host gradients explicitly before
                # the jitted apply: feeding unsharded numpy would force
                # XLA to re-infer layout from donated params and
                # materialize a replicated copy on every device first.
                grads = {
                    k: jax.device_put(v, grad_shardings[k])
                    for k, v in bundle["grads"].items()
                }
                # keep the return contract uniform across modes: loss
                # is always a jax scalar
                loss = jnp.asarray(bundle["loss"])
        new_params, opt_state = apply(params, opt_state, grads)
        return new_params, opt_state, loss

    return init_opt_state, step, param_specs
