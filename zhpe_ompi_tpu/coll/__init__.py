"""Collective framework: components (tpu/tuned/basic) + algorithm library."""
from . import algorithms, framework

__all__ = ["algorithms", "framework"]
