"""OpenSHMEM-analog PGAS layer (reference: ``oshmem/``, SURVEY.md §2.5).

The reference implements OpenSHMEM 1.4 over five frameworks: ``memheap``
(symmetric heap, buddy/ptmalloc allocators), ``sshmem`` (segment creation,
mmap/sysv), ``spml`` (put/get transport over UCX), ``atomic`` (AMOs) and
``scoll`` (collectives, including ``scoll/mpi`` which reuses the MPI
collective layer).  The TPU-native redesign keeps the same layering on the
host plane:

- :mod:`.memheap` — deterministic first-fit symmetric allocator: the same
  allocation sequence on every PE yields the same offsets, which is the
  entire symmetric-heap contract (``oshmem/mca/memheap``).
- :mod:`.api` — the PE-facing API (put/get/p/g, AMOs, wait_until, locks,
  broadcast/collect/reductions, barrier) — the analog of
  ``oshmem/shmem/c``'s 56 files over spml/scoll.
- :mod:`.spml` — the transport framework as REAL MCA components with
  priority selection: ``direct`` (thread ranks, shared address space),
  ``mmap`` (same-host OS processes over mapped tmpfs segments with
  native atomics, :mod:`.segment`), ``am`` (cross-host active messages).
  :func:`shmem_pe` is the spml-selected shmem_init.

On the device plane, symmetric objects are simply replicated/sharded jax
arrays and put/get lower to the same ``ppermute``/collective machinery as
:mod:`zhpe_ompi_tpu.coll` — PGAS and MPI converge on SPMD hardware, so no
separate device transport exists (documented design decision, not an
omission).
"""

from .api import (  # noqa: F401
    ShmemPE,
    shmem_mapped_pe,
    shmem_universe,
    shmem_wire_pe,
)
from .memheap import SymmetricHeapAllocator  # noqa: F401
from .spml import shmem_pe  # noqa: F401
