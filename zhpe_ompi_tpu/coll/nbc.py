"""Nonblocking collectives: libnbc-style round schedules on the host plane.

The reference ships a full nonblocking collective engine that compiles each
collective into a *schedule* of rounds — every round a set of send/recv/op
actions, progressed round-at-a-time by the request machinery
(``ompi/mca/coll/libnbc/nbc.c:1-80``, ``nbc_internal.h``).  This module is
that engine re-designed for Python: a schedule is a **generator** that
yields one round's sub-requests at a time; :class:`SchedRequest` advances
the generator whenever every yielded request has completed (the
NBC_PROGRESS analog), and the generator's return value completes the
collective's request.  The generator form subsumes libnbc's
NBC_Sched_send/recv/op/copy/barrier primitives: sequential yields ARE the
round barriers, and arbitrary Python between yields is the op/copy rounds.

Device-plane nonblocking collectives are a platform non-problem by design:
inside a jit trace every XLA collective is already asynchronous (the
scheduler overlaps it with unrelated compute), and ``jax.Array`` IS the
request handle — ``block_until_ready`` is Wait.  Documented in PARITY.md.

All schedules run over the same endpoint surface as
:mod:`zhpe_ompi_tpu.coll.host` (universe RankContext, TcpProc) and use its
per-instance collective tags (:func:`~zhpe_ompi_tpu.coll.host._next_tag`):
every collective — blocking or not — stamps its wire traffic with a
sequence number that is identical on every rank (same program order) and
unique per instance, so arbitrarily-overlapping schedules can never
cross-match (libnbc's ``schedule->tag`` mechanism).
"""

from __future__ import annotations

from typing import Any, Generator

from ..core import errors
from ..pt2pt.requests import Request
from ..runtime import ztrace
from . import host as H

# Nonblocking barrier's base kind tag (blocking barrier has its own
# reserved cid; the nonblocking one lives in the collective tag space).
TAG_IBARRIER = 0x7E0A


class SchedRequest(Request):
    """A collective request driven by a round-schedule generator.

    The generator yields lists of sub-requests (one list per round); it is
    resumed with the list of their payloads once all complete.  Its return
    value becomes this request's value.  Progress is weak (driven from
    wait/test), like every request in this framework.

    Revoke-aware (ULFM): on an ft-enabled endpoint, every progress tick
    and every round boundary checks whether the collective channel has
    been revoked — a rank parked inside a multi-round schedule (its
    partner died and will never send) aborts with typed ``Revoked`` as
    soon as the revocation lands, instead of discovering it only at its
    next pt2pt op (which, parked mid-wait, would be never).  The
    recovering rank triggers this by revoking the collective cid
    (``ep.revoke(coll.host.COLL_CID)``), the MPIX_Comm_revoke idiom.
    """

    __slots__ = ("_gen", "_round", "_endpoint_progress", "_ft_state",
                 "_coll_cid", "_tspan")

    def __init__(self, gen: Generator, endpoint_progress=None,
                 ft_state=None, coll_cid: int = H.COLL_CID,
                 trace_rank: int = -1, trace_op: "str | None" = None):
        super().__init__(progress=self._advance)
        self._gen = gen
        self._round: list[Request] = []
        self._endpoint_progress = endpoint_progress
        self._ft_state = ft_state
        self._coll_cid = coll_cid
        # tracing plane: one COLL span per schedule, issue → clean
        # completion (an aborted schedule records no span — the
        # missing span is the postmortem signal, like han's)
        self._tspan = ztrace.begin(
            ztrace.COLL, trace_rank, op=trace_op or "nbc", sched="nbc",
        ) if ztrace.active else None
        self._kick()

    def _finish(self, value) -> None:
        self.complete(value)
        if self._tspan is not None:
            self._tspan.end()
            self._tspan = None

    def _kick(self) -> None:
        """Start the schedule: run until the first yield (round 0 posted)."""
        try:
            self._round = list(next(self._gen))
        except StopIteration as stop:
            self._finish(stop.value)

    def _check_revoked(self) -> None:
        if self._ft_state is not None \
                and self._ft_state.is_revoked(self._coll_cid):
            raise errors.Revoked(
                f"collective schedule aborted: cid={self._coll_cid} "
                f"revoked mid-schedule", cid=self._coll_cid,
            )

    def _advance(self) -> None:
        """NBC_PROGRESS: if the current round is fully complete, feed the
        results back and post the next round(s).  Driving sub-request
        progress matters on the deferred engine: a parked isend whose
        peer died (or whose cid was revoked) classifies from ITS
        progress tick, and the typed error a sub-request completed with
        aborts the schedule at the round boundary — waitall observes
        the failure at completion, never a wedge."""
        if self.done:
            return
        self._check_revoked()
        if self._endpoint_progress is not None:
            self._endpoint_progress()
        for r in self._round:
            if not r.done and r._progress is not None:
                r._progress()
        while not self.done and all(r.done for r in self._round):
            self._check_revoked()  # round boundary
            err = next((r.error for r in self._round
                        if r.error is not None), None)
            if err is not None:
                # a sub-request completed ERRORED (typed peer death /
                # revocation from the deferred engine): the schedule
                # cannot make progress — abort typed, like the revoke
                # path, and surface the error at this request's wait
                self._gen.close()
                self.complete_error(err)
                return
            values = [r._value for r in self._round]
            try:
                self._round = list(self._gen.send(values))
            except StopIteration as stop:
                self._finish(stop.value)
            except BaseException as e:
                # the schedule body itself failed (e.g. a sub-send
                # raising at issue time): that error is the request's
                # PERMANENT outcome — without recording it, a later
                # test()/wait() would resume the dead generator into a
                # StopIteration(None) and report silent success
                self.complete_error(e)
                raise


def _start(ctx, gen, op: "str | None" = None) -> SchedRequest:
    if op is None and ztrace.active:
        # the public i<op> wrapper one frame up names the schedule —
        # resolved only while tracing is armed (disarmed calls pay
        # nothing for a label nobody records)
        import sys

        op = sys._getframe(1).f_code.co_name
    return SchedRequest(
        gen,
        endpoint_progress=getattr(ctx, "progress", None),
        ft_state=getattr(ctx, "ft_state", None),
        trace_rank=getattr(ctx, "rank", -1),
        trace_op=op,
    )


# ---------------------------------------------------------------- ibarrier


def ibarrier(ctx) -> SchedRequest:
    """Nonblocking dissemination barrier (the shape of
    coll_base_barrier.c's doubling, one yield per round)."""
    def sched():
        n, rank = ctx.size, ctx.rank
        tag = H._next_tag(ctx, TAG_IBARRIER)
        k = 1
        while k < n:
            rreq = ctx.irecv((rank - k) % n, tag=tag, cid=H.COLL_CID)
            sreq = ctx.isend(b"", (rank + k) % n, tag=tag, cid=H.COLL_CID)
            yield [rreq, sreq]
            k <<= 1
        return None

    return _start(ctx, sched())


# ------------------------------------------------------------------ ibcast


def ibcast(ctx, obj: Any = None, root: int = 0) -> SchedRequest:
    """Nonblocking binomial broadcast; request value is the payload."""
    def sched():
        size, rank = ctx.size, ctx.rank
        payload = obj
        if size > 1:
            tag = H._next_tag(ctx, H.TAG_BCAST)
            vrank = (rank - root) % size
            if vrank != 0:
                parent = ((vrank & (vrank - 1)) + root) % size
                (payload,) = (yield [
                    ctx.irecv(parent, tag=tag, cid=H.COLL_CID)
                ])
            sends = []
            mask = 1
            while mask < size:
                if vrank & (mask - 1) == 0 and vrank | mask != vrank:
                    child = vrank | mask
                    if child < size:
                        sends.append(ctx.isend(
                            payload, (child + root) % size,
                            tag=tag, cid=H.COLL_CID,
                        ))
                mask <<= 1
            if sends:
                yield sends
        return payload

    return _start(ctx, sched())


# -------------------------------------------------------------- iallreduce


def iallreduce(ctx, value: Any, op) -> SchedRequest:
    """Nonblocking recursive-doubling allreduce with the non-power-of-two
    fold — the same schedule as the blocking variant, one yield per
    communication round."""
    def sched():
        size, rank = ctx.size, ctx.rank
        acc = value
        if size == 1:
            return acc
        tag = H._next_tag(ctx, H.TAG_ALLREDUCE)
        pof2 = 1
        while pof2 * 2 <= size:
            pof2 *= 2
        rem = size - pof2
        if rank < 2 * rem:
            if rank % 2 == 0:
                yield [ctx.isend(acc, rank + 1, tag=tag,
                                 cid=H.COLL_CID)]
                newrank = -1
            else:
                (other,) = (yield [
                    ctx.irecv(rank - 1, tag=tag, cid=H.COLL_CID)
                ])
                acc = H._ordered(op, other, acc)
                newrank = rank // 2
        else:
            newrank = rank - rem
        if newrank >= 0:
            mask = 1
            while mask < pof2:
                pnew = newrank ^ mask
                partner = pnew * 2 + 1 if pnew < rem else pnew + rem
                rreq = ctx.irecv(partner, tag=tag,
                                 cid=H.COLL_CID)
                sreq = ctx.isend(acc, partner, tag=tag,
                                 cid=H.COLL_CID)
                other, _ = (yield [rreq, sreq])
                if partner < rank:
                    acc = H._ordered(op, other, acc)
                else:
                    acc = H._ordered(op, acc, other)
                mask <<= 1
        if rank < 2 * rem:
            if rank % 2 == 0:
                (acc,) = (yield [
                    ctx.irecv(rank + 1, tag=tag, cid=H.COLL_CID)
                ])
            else:
                yield [ctx.isend(acc, rank - 1, tag=tag,
                                 cid=H.COLL_CID)]
        return acc

    return _start(ctx, sched())


# -------------------------------------------------------------- iallgather


def iallgather(ctx, value: Any) -> SchedRequest:
    """Nonblocking ring allgather; request value is the rank-indexed list."""
    def sched():
        size, rank = ctx.size, ctx.rank
        out: list = [None] * size
        out[rank] = value
        tag = H._next_tag(ctx, H.TAG_ALLGATHER)
        right, left = (rank + 1) % size, (rank - 1) % size
        blk = (rank, value)
        for _ in range(size - 1):
            rreq = ctx.irecv(left, tag=tag, cid=H.COLL_CID)
            sreq = ctx.isend(blk, right, tag=tag, cid=H.COLL_CID)
            got, _ = (yield [rreq, sreq])
            out[got[0]] = got[1]
            blk = got
        return out

    return _start(ctx, sched())


# --------------------------------------------------------------- ialltoall


def ialltoall(ctx, values: list) -> SchedRequest:
    """Nonblocking pairwise-exchange alltoall; request value is the
    rank-indexed receive list."""
    if len(values) != ctx.size:
        raise errors.ArgError(f"ialltoall needs {ctx.size} blocks")

    def sched():
        size, rank = ctx.size, ctx.rank
        out: list = [None] * size
        out[rank] = values[rank]
        tag = H._next_tag(ctx, H.TAG_ALLTOALL)
        for i in range(1, size):
            sendto = (rank + i) % size
            recvfrom = (rank - i) % size
            rreq = ctx.irecv(recvfrom, tag=tag, cid=H.COLL_CID)
            sreq = ctx.isend(values[sendto], sendto, tag=tag,
                             cid=H.COLL_CID)
            got, _ = (yield [rreq, sreq])
            out[recvfrom] = got
        return out

    return _start(ctx, sched())


# ----------------------------------------------------------------- ireduce


def ireduce(ctx, value: Any, op, root: int = 0) -> SchedRequest:
    """Nonblocking reduce (binomial for commutative ops, in-order linear
    otherwise); request value significant at root."""
    def sched_linear():
        size, rank = ctx.size, ctx.rank
        tag = H._next_tag(ctx, H.TAG_REDUCE)
        if rank != root:
            yield [ctx.isend(value, root, tag=tag, cid=H.COLL_CID)]
            return None
        acc = None
        for r in range(size):
            if r == root:
                contrib = value
            else:
                (contrib,) = (yield [
                    ctx.irecv(r, tag=tag, cid=H.COLL_CID)
                ])
            acc = contrib if acc is None else H._ordered(op, acc, contrib)
        return acc

    def sched_binomial():
        size, rank = ctx.size, ctx.rank
        tag = H._next_tag(ctx, H.TAG_REDUCE)
        vrank = (rank - root) % size
        acc = value
        mask = 1
        while mask < size:
            if vrank & mask:
                parent = ((vrank & ~mask) + root) % size
                yield [ctx.isend((vrank, acc), parent, tag=tag,
                                 cid=H.COLL_CID)]
                return None
            child = vrank | mask
            if child < size:
                (got,) = (yield [
                    ctx.irecv((child + root) % size, tag=tag,
                              cid=H.COLL_CID)
                ])
                acc = H._ordered(op, acc, got[1])
            mask <<= 1
        return acc

    if ctx.size == 1:
        def sched_one():
            return value
            yield  # pragma: no cover - makes this a generator

        return _start(ctx, sched_one())
    gen = (sched_linear() if not getattr(op, "commute", True)
           else sched_binomial())
    return _start(ctx, gen)


# --------------------------------------------------------- igather/iscatter


def igather(ctx, value: Any, root: int = 0) -> SchedRequest:
    """Nonblocking linear gather; request value is the list at root."""
    def sched():
        size, rank = ctx.size, ctx.rank
        tag = H._next_tag(ctx, H.TAG_GATHER)
        if rank != root:
            yield [ctx.isend(value, root, tag=tag, cid=H.COLL_CID)]
            return None
        out = [None] * size
        out[root] = value
        others = [r for r in range(size) if r != root]
        reqs = [ctx.irecv(r, tag=tag, cid=H.COLL_CID)
                for r in others]
        vals = yield reqs
        for r, v in zip(others, vals):
            out[r] = v
        return out

    return _start(ctx, sched())


def iscatter(ctx, values: list | None = None, root: int = 0) -> SchedRequest:
    """Nonblocking linear scatter; request value is this rank's block."""
    if ctx.rank == root and (values is None or len(values) != ctx.size):
        raise errors.ArgError(
            f"iscatter root needs {ctx.size} blocks, got "
            f"{'None' if values is None else len(values)}"
        )

    def sched():
        size, rank = ctx.size, ctx.rank
        tag = H._next_tag(ctx, H.TAG_SCATTER)
        if rank == root:
            reqs = [ctx.isend(values[r], r, tag=tag, cid=H.COLL_CID)
                    for r in range(size) if r != root]
            if reqs:
                yield reqs
            return values[root]
        (blk,) = (yield [ctx.irecv(root, tag=tag, cid=H.COLL_CID)])
        return blk

    return _start(ctx, sched())


# ------------------------------------------------- v-variant schedules
# (libnbc's nbc_iallgatherv.c / nbc_ialltoallv.c set — round-3 fill-in)


def iallgatherv(ctx, value: Any) -> SchedRequest:
    """Nonblocking ring allgatherv: blocks carry their sizes, so the
    schedule is the allgather ring verbatim (nbc_iallgatherv.c shape)."""
    def sched():
        size, rank = ctx.size, ctx.rank
        out: list = [None] * size
        out[rank] = value
        if size == 1:
            return out
        tag = H._next_tag(ctx, H.TAG_ALLGATHERV)
        right, left = (rank + 1) % size, (rank - 1) % size
        blk = (rank, value)
        for _ in range(size - 1):
            rreq = ctx.irecv(left, tag=tag, cid=H.COLL_CID)
            sreq = ctx.isend(blk, right, tag=tag, cid=H.COLL_CID)
            got, _ = (yield [rreq, sreq])
            out[got[0]] = got[1]
            blk = got
        return out

    return _start(ctx, sched())


def ialltoallv(ctx, sendbuf, counts: list, displs: list | None = None
               ) -> SchedRequest:
    """Nonblocking pairwise alltoallv over a flat buffer + counts
    (nbc_ialltoallv.c shape); request value is the rank-indexed recv
    list."""
    blocks = H._blocks_from(sendbuf, counts, displs, ctx.size)

    def sched():
        size, rank = ctx.size, ctx.rank
        out: list = [None] * size
        out[rank] = blocks[rank]
        tag = H._next_tag(ctx, H.TAG_ALLTOALLV)
        for i in range(1, size):
            sendto = (rank + i) % size
            recvfrom = (rank - i) % size
            rreq = ctx.irecv(recvfrom, tag=tag, cid=H.COLL_CID)
            sreq = ctx.isend(blocks[sendto], sendto, tag=tag,
                             cid=H.COLL_CID)
            got, _ = (yield [rreq, sreq])
            out[recvfrom] = got
        return out

    return _start(ctx, sched())


def igatherv(ctx, value: Any, root: int = 0) -> SchedRequest:
    """Nonblocking linear gatherv (variable-size blocks)."""
    def sched():
        size, rank = ctx.size, ctx.rank
        tag = H._next_tag(ctx, H.TAG_GATHERV)
        if rank != root:
            yield [ctx.isend(value, root, tag=tag, cid=H.COLL_CID)]
            return None
        out = [None] * size
        out[root] = value
        others = [r for r in range(size) if r != root]
        vals = yield [ctx.irecv(r, tag=tag, cid=H.COLL_CID)
                      for r in others]
        for r, v in zip(others, vals):
            out[r] = v
        return out

    return _start(ctx, sched())


def iscatterv(ctx, sendbuf=None, counts: list | None = None,
              displs: list | None = None, root: int = 0) -> SchedRequest:
    """Nonblocking linear scatterv (flat buffer + counts at root)."""
    if ctx.rank == root:
        if sendbuf is None or counts is None:
            raise errors.ArgError(
                f"iscatterv root needs a buffer and {ctx.size} counts"
            )
        blocks = H._blocks_from(sendbuf, counts, displs, ctx.size)

    def sched():
        size, rank = ctx.size, ctx.rank
        tag = H._next_tag(ctx, H.TAG_SCATTERV)
        if rank == root:
            reqs = [ctx.isend(blocks[r], r, tag=tag, cid=H.COLL_CID)
                    for r in range(size) if r != root]
            if reqs:
                yield reqs
            return blocks[root]
        (blk,) = (yield [ctx.irecv(root, tag=tag, cid=H.COLL_CID)])
        return blk

    return _start(ctx, sched())


# ------------------------------------------------ scan/exscan schedules
# (nbc_iscan.c / nbc_iexscan.c: linear chain, one neighbor hop per rank)


def iscan(ctx, value: Any, op) -> SchedRequest:
    """Nonblocking inclusive prefix reduction (chain schedule)."""
    def sched():
        rank = ctx.rank
        tag = H._next_tag(ctx, H.TAG_SCAN)
        acc = value
        if rank > 0:
            (prev,) = (yield [
                ctx.irecv(rank - 1, tag=tag, cid=H.COLL_CID)
            ])
            acc = H._ordered(op, prev, acc)
        if rank + 1 < ctx.size:
            yield [ctx.isend(acc, rank + 1, tag=tag, cid=H.COLL_CID)]
        return acc

    return _start(ctx, sched())


def iexscan(ctx, value: Any, op) -> SchedRequest:
    """Nonblocking exclusive prefix reduction; rank 0's value is None."""
    def sched():
        rank = ctx.rank
        tag = H._next_tag(ctx, H.TAG_SCAN)
        prev = None
        if rank > 0:
            (prev,) = (yield [
                ctx.irecv(rank - 1, tag=tag, cid=H.COLL_CID)
            ])
        if rank + 1 < ctx.size:
            mine = value if prev is None else H._ordered(op, prev, value)
            yield [ctx.isend(mine, rank + 1, tag=tag, cid=H.COLL_CID)]
        return prev

    return _start(ctx, sched())


# --------------------------------------------- reduce_scatter schedules
# (nbc_ireduce_scatter.c: reduce + scatterv pipeline)


def ireduce_scatter(ctx, values: list, op) -> SchedRequest:
    """Nonblocking blockwise reduce + scatter: `values` is the
    rank-indexed block list; request value is this rank's fully-reduced
    block."""
    if len(values) != ctx.size:
        raise errors.ArgError(f"ireduce_scatter needs {ctx.size} blocks")

    def sched():
        size, rank = ctx.size, ctx.rank
        if size == 1:
            return values[0]
        # binomial reduce of the block list to rank 0 (in-order combines)
        tag = H._next_tag(ctx, H.TAG_RSCAT)
        acc = list(values)
        vrank = rank
        mask = 1
        while mask < size:
            if vrank & mask:
                yield [ctx.isend((vrank, acc), vrank & ~mask, tag=tag,
                                 cid=H.COLL_CID)]
                break
            child = vrank | mask
            if child < size:
                (got,) = (yield [
                    ctx.irecv(child, tag=tag, cid=H.COLL_CID)
                ])
                acc = H._combine(op, acc, got[1])
            mask <<= 1
        # scatter the reduced blocks from rank 0
        stag = H._next_tag(ctx, H.TAG_SCATTER)
        if rank == 0:
            reqs = [ctx.isend(acc[r], r, tag=stag, cid=H.COLL_CID)
                    for r in range(1, size)]
            if reqs:
                yield reqs
            return acc[0]
        (blk,) = (yield [ctx.irecv(0, tag=stag, cid=H.COLL_CID)])
        return blk

    return _start(ctx, sched())


def ireduce_scatter_block(ctx, values: list, op) -> SchedRequest:
    """Nonblocking reduce_scatter_block: equal block counts — the MPI
    surface distinction; the schedule is shared."""
    return ireduce_scatter(ctx, values, op)


# ----------------------------------------------- neighbor collectives
# (nbc_ineighbor_allgather.c / nbc_ineighbor_alltoall.c: one round of
# irecv from every in-neighbor + isend to every out-neighbor)


def ineighbor_allgather(ctx, value: Any, sources: list[int],
                        destinations: list[int]) -> SchedRequest:
    """Nonblocking neighbor allgather over explicit neighbor lists (the
    dist_graph adjacency): sends `value` to every destination, returns
    the in-neighbor-ordered list of received values."""
    def sched():
        tag = H._next_tag(ctx, H.TAG_NEIGHBOR)
        rreqs = [ctx.irecv(s, tag=tag, cid=H.COLL_CID) for s in sources]
        sreqs = [ctx.isend(value, d, tag=tag, cid=H.COLL_CID)
                 for d in destinations]
        vals = yield rreqs + sreqs
        return list(vals[: len(rreqs)])

    return _start(ctx, sched())


def ineighbor_alltoall(ctx, values: list, sources: list[int],
                       destinations: list[int]) -> SchedRequest:
    """Nonblocking neighbor alltoall: values[i] goes to destinations[i];
    returns the in-neighbor-ordered received list."""
    if len(values) != len(destinations):
        raise errors.ArgError(
            "ineighbor_alltoall needs one value per destination"
        )

    def sched():
        tag = H._next_tag(ctx, H.TAG_NEIGHBOR)
        rreqs = [ctx.irecv(s, tag=tag, cid=H.COLL_CID) for s in sources]
        sreqs = [ctx.isend(v, d, tag=tag, cid=H.COLL_CID)
                 for v, d in zip(values, destinations)]
        vals = yield rreqs + sreqs
        return list(vals[: len(rreqs)])

    return _start(ctx, sched())


class NonblockingCollectives:
    """Mixin: the MPI_Ix surface for host endpoints (pairs with
    :class:`zhpe_ompi_tpu.coll.host.HostCollectives`)."""

    def ibarrier(self) -> SchedRequest:
        return ibarrier(self)

    def ibcast(self, obj: Any = None, root: int = 0) -> SchedRequest:
        return ibcast(self, obj, root)

    def iallreduce(self, value: Any, op) -> SchedRequest:
        return iallreduce(self, value, op)

    def iallgather(self, value: Any) -> SchedRequest:
        return iallgather(self, value)

    def ialltoall(self, values: list) -> SchedRequest:
        return ialltoall(self, values)

    def ireduce(self, value: Any, op, root: int = 0) -> SchedRequest:
        return ireduce(self, value, op, root)

    def igather(self, value: Any, root: int = 0) -> SchedRequest:
        return igather(self, value, root)

    def iscatter(self, values: list | None = None, root: int = 0
                 ) -> SchedRequest:
        return iscatter(self, values, root)

    def iallgatherv(self, value: Any) -> SchedRequest:
        return iallgatherv(self, value)

    def ialltoallv(self, sendbuf, counts: list,
                   displs: list | None = None) -> SchedRequest:
        return ialltoallv(self, sendbuf, counts, displs)

    def igatherv(self, value: Any, root: int = 0) -> SchedRequest:
        return igatherv(self, value, root)

    def iscatterv(self, sendbuf=None, counts: list | None = None,
                  displs: list | None = None, root: int = 0
                  ) -> SchedRequest:
        return iscatterv(self, sendbuf, counts, displs, root)

    def iscan(self, value: Any, op) -> SchedRequest:
        return iscan(self, value, op)

    def iexscan(self, value: Any, op) -> SchedRequest:
        return iexscan(self, value, op)

    def ireduce_scatter(self, values: list, op) -> SchedRequest:
        return ireduce_scatter(self, values, op)

    def ireduce_scatter_block(self, values: list, op) -> SchedRequest:
        return ireduce_scatter_block(self, values, op)

    def ineighbor_allgather(self, value: Any, sources: list[int],
                            destinations: list[int]) -> SchedRequest:
        return ineighbor_allgather(self, value, sources, destinations)

    def ineighbor_alltoall(self, values: list, sources: list[int],
                           destinations: list[int]) -> SchedRequest:
        return ineighbor_alltoall(self, values, sources, destinations)

    # blocking neighbor collectives (MPI_Neighbor_allgather/alltoall):
    # the schedule run to completion — same layering the reference gets
    # from nbc_ineighbor_* + wait
    def neighbor_allgather(self, value: Any, sources: list[int],
                           destinations: list[int]) -> list:
        return ineighbor_allgather(self, value, sources,
                                   destinations).wait()

    def neighbor_alltoall(self, values: list, sources: list[int],
                          destinations: list[int]) -> list:
        return ineighbor_alltoall(self, values, sources,
                                  destinations).wait()
