"""MFU lever sweep on the real chip: batch x remat x the round-4 levers
(fused Pallas layernorm, vocab-chunked CE) for the headline config.
Steady-state discipline from bench.py (burn-in window, median of 3).

Run from repo root: python benchmarks/mfu_sweep.py
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu.models import transformer as tfm

    import bench

    devs = jax.devices()
    mesh = Mesh(np.asarray(devs[:1]).reshape(1, 1), ("dp", "tp"))
    dp_comm = zmpi.Communicator(mesh, "dp", name="sweep_dp")

    peak, _ = bench._chip_peak(devs[0])

    # (batch, remat, seq, fused_ln, ce_chunk): the round-3 grid plus the
    # round-4 levers individually and together at the measured optimum
    for batch, remat, seq, fused_ln, ce_chunk in [
        (8, False, 512, False, None), (16, False, 512, False, None),
        (32, False, 512, False, None), (16, True, 512, False, None),
        (32, True, 512, False, None), (64, True, 512, False, None),
        # levers, one at a time then together, at B16/B32 + remat
        (16, True, 512, None, None), (16, True, 512, False, 1024),
        (16, True, 512, None, 1024), (32, True, 512, None, 1024),
        (16, True, 512, None, 512), (16, True, 512, None, 2048),
    ]:
        cfg = tfm.Config(
            vocab=8192, d_model=1024, n_heads=16, d_ff=4096, n_layers=4,
            seq=seq, dtype=jnp.bfloat16, remat=remat, fused_ln=fused_ln,
            ce_chunk=ce_chunk,
        )
        r = np.random.default_rng(0)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        tok = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
        tgt = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
        step, specs = tfm.make_train_step(cfg, mesh, dp_comm, None)
        sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                   for k, v in params.items()}
        dspec = NamedSharding(mesh, P("dp"))
        tokd, tgtd = jax.device_put(tok, dspec), jax.device_put(tgt, dspec)
        try:
            ps, loss = step(sharded, tokd, tgtd)
            for _ in range(3):
                ps, loss = step(ps, tokd, tgtd)
            float(loss)
            iters = max(4, int(0.5 / (0.003 * batch)))
            times = []
            for w in range(4):  # first window discarded
                t0 = time.perf_counter()
                for _ in range(iters):
                    ps, loss = step(ps, tokd, tgtd)
                float(loss)
                if w > 0:
                    times.append((time.perf_counter() - t0) / iters)
            med = float(np.median(times))
            fl = bench._train_flops_per_step(cfg, batch)
            lev = f"ln={'auto' if fused_ln is None else int(fused_ln)} " \
                  f"ce={ce_chunk or 0}"
            print(f"B={batch:3d} remat={int(remat)} seq={seq} {lev}: "
                  f"{med*1e3:7.2f} ms  {batch*seq/med:9.0f} tok/s  "
                  f"MFU {fl/med/peak*100:5.2f}%", flush=True)
        except Exception as e:
            print(f"B={batch:3d} remat={int(remat)} seq={seq}: FAILED "
                  f"{type(e).__name__}: {str(e)[:120]}", flush=True)


if __name__ == "__main__":
    main()
