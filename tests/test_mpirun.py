"""zmpirun launcher tests — the reference's launch surface
(mpirun → prte, ``ompi/tools/mpirun/Makefile.am:11-15``) exercised the way
``test/simple/`` exercises it: tiny programs under the launcher, plus the
abort/teardown path (``test/simple/delayed_abort.c`` shape).

These spawn REAL OS processes; every rank's endpoint comes up through the
ZMPI_* env contract via zmpi.host_init().
"""

import io
import os
import sys
import textwrap

import pytest

from zhpe_ompi_tpu.tools import mpirun

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script(tmp_path, body: str) -> str:
    p = tmp_path / "prog.py"
    p.write_text(
        "import sys\n"
        f"sys.path.insert(0, {_REPO!r})\n" + textwrap.dedent(body)
    )
    return str(p)


def _launch(n, argv, timeout=60.0, **kw):
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(n, argv, stdout=out, stderr=err, timeout=timeout,
                       **kw)
    return rc, out.getvalue(), err.getvalue()


def test_ring_example():
    rc, out, err = _launch(
        3, [os.path.join(_REPO, "examples", "zmpirun_ring.py")]
    )
    assert rc == 0, err
    assert "PASSED" in out
    # IOF prefixes: rank 0's lines carry the [0] tag
    assert "[0] " in out


def test_collectives_across_processes(tmp_path):
    prog = _script(tmp_path, """
        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu import ops as zops

        proc = zmpi.host_init()
        vals = proc.allgather(proc.rank * 10)
        assert vals == [0, 10, 20], vals
        got = proc.bcast("hello" if proc.rank == 1 else None, root=1)
        assert got == "hello"
        m = proc.allreduce(proc.rank, zops.MAX)
        assert m == proc.size - 1
        print(f"rank {proc.rank} OK")
        zmpi.host_finalize()
    """)
    rc, out, err = _launch(3, [prog])
    assert rc == 0, err
    assert out.count("OK") == 3


def test_abort_tears_down_job(tmp_path):
    # one rank exits nonzero; the launcher must kill the others (which
    # block forever) and surface the failing code — MPI_Abort semantics
    prog = _script(tmp_path, """
        import sys, time
        import zhpe_ompi_tpu as zmpi

        proc = zmpi.host_init()
        if proc.rank == 1:
            sys.exit(7)
        time.sleep(600)
    """)
    rc, out, err = _launch(3, [prog])
    assert rc == 7
    assert "rank 1 exited with code 7" in err


def test_mca_forwarding(tmp_path):
    prog = _script(tmp_path, """
        import zhpe_ompi_tpu as zmpi

        proc = zmpi.host_init()  # imports pt2pt.tcp, registering tcp_* vars
        val = zmpi.mca_var.get("tcp_eager_limit", None)
        print(f"rank {proc.rank} eager={val}")
        zmpi.host_finalize()
    """)
    rc, out, err = _launch(2, [prog], mca=[("tcp_eager_limit", "4096")])
    assert rc == 0, err
    assert out.count("eager=4096") == 2


def test_job_timeout(tmp_path):
    prog = _script(tmp_path, """
        import time
        time.sleep(600)
    """)
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(2, [prog], stdout=out, stderr=err, timeout=3.0)
    assert rc == 124
    assert "timeout" in err.getvalue()


def test_cli_entrypoint(tmp_path):
    # python -m zhpe_ompi_tpu.tools.mpirun parses and runs end to end
    import subprocess

    prog = _script(tmp_path, "print('cli-ok')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, "-m", "zhpe_ompi_tpu.tools.mpirun",
         "-n", "2", "--no-tag-output", prog],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert res.returncode == 0, res.stderr
    assert res.stdout.count("cli-ok") == 2


def test_c_program_under_launcher(tmp_path):
    """A compiled C rank (ABI shim) launches under zmpirun: the shim's
    MPI_Init honors ZMPI_COORD_EXTERNAL and joins the launcher-hosted
    rendezvous as a client — C and the launcher speak one wire-up."""
    import subprocess

    from zhpe_ompi_tpu.tools import zmpicc

    binary = tmp_path / "ring_c"
    subprocess.run(
        ["gcc", os.path.join(_REPO, "examples", "ring_c.c"),
         "-o", str(binary)] + zmpicc.compile_flags() + zmpicc.link_flags(),
        check=True, capture_output=True, text=True,
    )
    rc, out, err = _launch(3, [str(binary)])
    assert rc == 0, err
    assert "PASSED" in out or "ring" in out.lower(), out


def test_name_publishing_across_ranks(tmp_path):
    """MPI_Publish_name/Lookup_name through the launcher-hosted name
    server (the ompi-server analog): one rank publishes, another looks
    the service up — discovery with no out-of-band exchange."""
    prog = _script(tmp_path, """
        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.comm import dpm_wire
        from zhpe_ompi_tpu.core import errors

        proc = zmpi.host_init()
        if proc.rank == 0:
            dpm_wire.publish_name("svc", "10.0.0.1:4242")
            proc.barrier()
            proc.barrier()  # rank 1 looked it up
            dpm_wire.unpublish_name("svc")
            proc.barrier()
        else:
            proc.barrier()
            assert dpm_wire.lookup_name("svc") == "10.0.0.1:4242"
            proc.barrier()
            proc.barrier()  # rank 0 unpublished
            try:
                dpm_wire.lookup_name("svc")
            except errors.ArgError:
                print("NS-OK")
            else:
                raise SystemExit("lookup after unpublish succeeded")
        zmpi.host_finalize()
    """)
    rc, out, err = _launch(2, [prog])
    assert rc == 0, err
    assert "NS-OK" in out


def test_zmpicc_wrapper_compile_and_launch(tmp_path):
    """zmpicc (the mpicc wrapper analog) compiles examples/ring_c.c with
    no manual flags, and the binary runs under zmpirun — the reference's
    whole C toolchain loop: wrapper compiler -> launcher."""
    import subprocess

    binary = str(tmp_path / "ring_c")
    res = subprocess.run(
        [sys.executable, "-m", "zhpe_ompi_tpu.tools.zmpicc",
         os.path.join(_REPO, "examples", "ring_c.c"), "-o", binary],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "PYTHONPATH": _REPO},
    )
    assert res.returncode == 0, res.stderr
    showme = subprocess.run(
        [sys.executable, "-m", "zhpe_ompi_tpu.tools.zmpicc", "--showme"],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "PYTHONPATH": _REPO},
    )
    assert "-lzompi_mpi" in showme.stdout
    rc, out, err = _launch(4, [binary])
    assert rc == 0, err


def test_mpmd_mixed_c_and_python(tmp_path):
    """MPMD (-n 1 C-binary : -n 2 python): one COMM_WORLD, mixed
    languages, one wire protocol.  The C rank (rank 0) sendrecvs with
    Python ranks through the shim."""
    import subprocess

    from zhpe_ompi_tpu.tools import zmpicc

    csrc = tmp_path / "head.c"
    csrc.write_text(textwrap.dedent("""
        #include <stdio.h>
        #include "zompi_mpi.h"
        int main(int argc, char **argv) {
            int rank, size, v;
            MPI_Init(&argc, &argv);
            MPI_Comm_rank(MPI_COMM_WORLD, &rank);
            MPI_Comm_size(MPI_COMM_WORLD, &size);
            for (int r = 1; r < size; r++) {
                v = 100 + r;
                MPI_Send(&v, 1, MPI_INT, r, 5, MPI_COMM_WORLD);
            }
            int total = 0;
            for (int r = 1; r < size; r++) {
                MPI_Status st;
                MPI_Recv(&v, 1, MPI_INT, r, 6, MPI_COMM_WORLD, &st);
                total += v;
            }
            printf("HEAD total=%d\\n", total);
            MPI_Finalize();
            return total == 406 ? 0 : 1;  /* 2*101 + 2*102 */
        }
    """))
    binary = str(tmp_path / "head")
    subprocess.run(
        ["gcc", str(csrc), "-o", binary]
        + zmpicc.compile_flags() + zmpicc.link_flags(),
        check=True, capture_output=True, text=True,
    )
    pyprog = _script(tmp_path, """
        import numpy as np
        import zhpe_ompi_tpu as zmpi

        proc = zmpi.host_init()
        got = proc.recv(source=0, tag=5)
        v = int(np.asarray(got).reshape(-1)[0])
        proc.send(np.asarray([2 * v], np.int32), 0, tag=6)
    """)
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch_mpmd(
        [(1, [binary]), (2, [pyprog])],
        stdout=out, stderr=err, timeout=120.0,
    )
    assert rc == 0, err.getvalue()
    assert "HEAD total=406" in out.getvalue()


def test_cli_mpmd_colon_syntax(tmp_path):
    import subprocess

    a = _script(tmp_path, "print('A-rank')\n")
    bp = tmp_path / "b.py"
    bp.write_text(
        f"import sys\nsys.path.insert(0, {_REPO!r})\nprint('B-rank')\n")
    b = str(bp)
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    res = subprocess.run(
        [sys.executable, "-m", "zhpe_ompi_tpu.tools.mpirun",
         "-n", "2", "--no-tag-output", a, ":", "-n", "1", b],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert res.returncode == 0, res.stderr
    assert res.stdout.count("A-rank") == 2
    assert res.stdout.count("B-rank") == 1


def test_zero_train_example():
    """ZeRO-1 example under the launcher: 2 slices, partitioned state,
    decreasing loss."""
    rc, out, err = _launch(
        2, [os.path.join(_REPO, "examples", "zmpirun_zero_train.py")],
        timeout=150.0,
    )
    assert rc == 0, err
    assert out.count("PASSED") == 2


def test_signal_hygiene_sigterm(tmp_path):
    """zmpirun signal hygiene: SIGTERM to the launcher is forwarded to
    the job, every child is reaped, the rendezvous port is released,
    and the launcher exits 128+sig — a Ctrl-C must not orphan ranks
    still holding sockets and /dev/shm rings."""
    import signal
    import subprocess
    import time

    pid_dir = tmp_path / "pids"
    pid_dir.mkdir()
    prog = _script(tmp_path, f"""
        import os, time
        open(os.path.join({str(pid_dir)!r}, str(os.getpid())), "w").close()
        time.sleep(600)
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.Popen(
        [sys.executable, "-m", "zhpe_ompi_tpu.tools.mpirun",
         "-n", "2", prog],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    try:
        deadline = time.monotonic() + 60.0
        while len(list(pid_dir.iterdir())) < 2:
            assert time.monotonic() < deadline, "ranks never started"
            assert p.poll() is None, p.communicate()
            time.sleep(0.05)
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=30.0)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert rc == 128 + signal.SIGTERM, p.communicate()
    # children reaped: no rank process may survive the launcher
    deadline = time.monotonic() + 10.0
    pids = [int(f.name) for f in pid_dir.iterdir()]
    while time.monotonic() < deadline:
        alive = []
        for pid in pids:
            try:
                os.kill(pid, 0)
                alive.append(pid)
            except ProcessLookupError:
                pass
        if not alive:
            break
        time.sleep(0.1)
    assert not alive, f"orphaned rank processes: {alive}"


class TestDvm:
    """Runtime-plane daemon (zprted) lifecycle matrix: a resident VM
    hosts the PMIx store across jobs, launches sequential jobs into
    itself, stops clean, and rides over a just-stopped predecessor's
    port (stale-socket retry)."""

    def _mod(self):
        from zhpe_ompi_tpu.runtime import dvm as dvm_mod
        return dvm_mod

    def _prog(self, tmp_path):
        return _script(tmp_path, """
            import zhpe_ompi_tpu as zmpi

            proc = zmpi.host_init()
            vals = proc.allgather(proc.rank + 1)
            assert vals == [1, 2], vals
            print(f"rank {proc.rank} OK")
            zmpi.host_finalize()
        """)

    def test_two_sequential_jobs_one_dvm(self, tmp_path):
        """Start → launch two jobs into ONE resident VM → stop: the
        store outlives each job (namespace destroyed at job end), the
        daemon outlives both."""
        from zhpe_ompi_tpu.runtime import pmix as pmix_mod
        from zhpe_ompi_tpu.runtime import spc

        dvm_mod = self._mod()
        prog = self._prog(tmp_path)
        jobs0 = spc.read("dvm_jobs_launched")
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            assert cli.ping()
            out, err = io.StringIO(), io.StringIO()
            rc1 = cli.launch(2, [prog], timeout=90.0, stdout=out,
                             stderr=err)
            job1 = cli.last_job_id
            rc2 = cli.launch(2, [prog], timeout=90.0, stdout=out,
                             stderr=err)
            assert (rc1, rc2) == (0, 0), err.getvalue()
            assert cli.last_job_id != job1  # a NEW job, same VM
            assert out.getvalue().count("OK") == 4
            stat = cli.stat()
            assert stat["dvm_jobs_launched"] - jobs0 == 2
            # per-job namespaces were destroyed when the jobs ended
            assert stat["pmix"] == {}
            cli.close()
        finally:
            d.stop()
        assert dvm_mod.live_dvms() == []
        assert pmix_mod.live_servers() == []
        assert pmix_mod.stale_namespaces() == []

    def test_starved_iof_drain_never_loses_the_final_line(
            self, tmp_path, monkeypatch):
        """The finalize-skew regression (intermittent in
        TestDvmMultiVictimRecovery since PR 11): job exit accounting
        fires on the last waitpid, but a rank's final stdout line is
        still in its pipe until the IOF drain THREAD pumps it — a
        drain starved by scheduler load past a short per-thread join
        bound lost the line to a client that stopped reading at the
        exit frame.  Starvation is simulated deterministically (the
        last rank's stdout drain sleeps 3 s before pumping — beyond
        the old 2 s bound, inside the shared _IOF_DRAIN_GRACE): the
        exit frame must WAIT, and every line must reach the client."""
        import time as time_mod

        dvm_mod = self._mod()
        prog = _script(tmp_path, """
            import os

            print(f"LAST-LINE rank={os.environ['ZMPI_RANK']}",
                  flush=True)
        """)
        orig = dvm_mod.Dvm._drain_iof

        def starved(self, job, rank, label, stream):
            if rank == 1 and label == "":
                time_mod.sleep(3.0)  # the starved scheduler slot
            orig(self, job, rank, label, stream)

        monkeypatch.setattr(dvm_mod.Dvm, "_drain_iof", starved)
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            out, err = io.StringIO(), io.StringIO()
            rc = cli.launch(2, [prog], timeout=60.0, stdout=out,
                            stderr=err)
            assert rc == 0, (out.getvalue(), err.getvalue())
            text = out.getvalue()
            for r in (0, 1):
                assert f"LAST-LINE rank={r}" in text, (
                    f"rank {r}'s final line raced the exit frame: "
                    f"{text!r}")
            cli.close()
        finally:
            d.stop()
        assert dvm_mod.live_dvms() == []

    def test_abort_semantics_in_dvm_job(self, tmp_path):
        """A non-ft daemon job keeps the zmpirun MPI_Abort contract:
        one rank exits nonzero, the daemon kills the rest and the job
        surfaces the failing code."""
        dvm_mod = self._mod()
        prog = _script(tmp_path, """
            import sys, time
            import zhpe_ompi_tpu as zmpi

            proc = zmpi.host_init()
            if proc.rank == 1:
                sys.exit(7)
            time.sleep(600)
        """)
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            out, err = io.StringIO(), io.StringIO()
            rc = cli.launch(3, [prog], timeout=90.0, stdout=out,
                            stderr=err)
            assert rc == 7
            assert "rank 1 exited with code 7" in err.getvalue()
            cli.close()
        finally:
            d.stop()

    def test_stop_then_rebind_same_ports(self):
        """Stale-socket retry: a daemon restarted onto the ports of a
        JUST-stopped predecessor must bind over the TIME_WAIT corpses
        (SO_REUSEADDR on both listeners)."""
        dvm_mod = self._mod()
        d1 = dvm_mod.Dvm()
        port, pmix_port = d1.address[1], d1.pmix.address[1]
        cli = dvm_mod.DvmClient(d1.address)
        assert cli.ping()
        assert cli.stop() is True  # stop via RPC, not object call
        cli.close()
        assert d1.wait(10.0)
        d2 = dvm_mod.Dvm(port=port, pmix_port=pmix_port)
        try:
            cli2 = dvm_mod.DvmClient(d2.address)
            assert cli2.ping()
            cli2.close()
        finally:
            d2.stop()
        assert dvm_mod.live_dvms() == []

    def test_zprted_subprocess_and_dvm_cli(self, tmp_path):
        """The real daemon shape: zprted as its OWN process (python -m
        zhpe_ompi_tpu.runtime.dvm), a job launched into it through the
        zmpirun --dvm CLI path, orderly stop, clean exit."""
        import subprocess

        dvm_mod = self._mod()
        prog = self._prog(tmp_path)
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        daemon = subprocess.Popen(
            [sys.executable, "-m", "zhpe_ompi_tpu.runtime.dvm"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        try:
            # bounded ready-line read: a daemon that dies before
            # printing must fail THIS test, not hang the suite
            import select

            r, _, _ = select.select([daemon.stdout], [], [], 60.0)
            assert r, "zprted never printed its ready line"
            ready = daemon.stdout.readline()
            assert ready.startswith("zprted ready"), (
                ready, daemon.stderr.read() if daemon.poll() else "")
            addr = ready.split("dvm=")[1].split()[0]
            out, err = io.StringIO(), io.StringIO()
            rc = mpirun.launch_dvm(addr, 2, [prog], timeout=90.0,
                                   stdout=out, stderr=err)
            assert rc == 0, err.getvalue()
            assert out.getvalue().count("OK") == 2
            cli = dvm_mod.DvmClient(addr)
            cli.stop()
            cli.close()
            assert daemon.wait(timeout=15.0) == 0
        finally:
            if daemon.poll() is None:
                daemon.kill()
                daemon.wait()
        assert dvm_mod.orphaned_daemon_processes() == []
