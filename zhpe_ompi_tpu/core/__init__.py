"""Core substrate: errors and small host-side data structures (OPAL-core analog)."""

from . import errors

__all__ = ["errors"]
