/* spawn_c — dynamic process management acceptance (comm_spawn.c):
 * every parent rank collectively spawns 2 children (this same binary,
 * re-exec'd in child mode), the children form their own
 * MPI_COMM_WORLD, and parent rank 0 round-trips a payload with each
 * child over the spawn intercommunicator.
 *
 *   python -m zhpe_ompi_tpu.tools.zmpicc examples/spawn_c.c -o spawn
 *   python -m zhpe_ompi_tpu.tools.mpirun -n 3 ./spawn ./spawn
 *
 * argv[1] is the child command (normally this binary's own path).
 */
#include <stdio.h>
#include <stdlib.h>
#include "zompi_mpi.h"

static int child_main(void) {
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  MPI_Comm parent;
  MPI_Comm_get_parent(&parent);
  if (parent == MPI_COMM_NULL) return 10;
  /* the children's world is their own: contexts disjoint from the
     parents' — prove it with a child-only allreduce */
  long v = rank + 1, sum = 0;
  MPI_Allreduce(&v, &sum, 1, MPI_LONG, MPI_SUM, MPI_COMM_WORLD);
  if (sum != (long)size * (size + 1) / 2) return 11;
  long got = -1;
  MPI_Recv(&got, 1, MPI_LONG, 0, 40, parent, MPI_STATUS_IGNORE);
  got = got * 10 + rank;
  MPI_Send(&got, 1, MPI_LONG, 0, 41, parent);
  MPI_Finalize();
  return 0;
}

int main(int argc, char **argv) {
  int rank, size;
  if (MPI_Init(&argc, &argv) != MPI_SUCCESS) return 2;
  if (getenv("ZMPI_WORLD_BASE")) return child_main();  /* spawned side */
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  const char *child = argc > 1 ? argv[1] : argv[0];
  MPI_Comm kids;
  int errs[2];
  if (MPI_Comm_spawn(child, NULL, 2, MPI_INFO_NULL, 0, MPI_COMM_WORLD,
                     &kids, errs) != MPI_SUCCESS) return 3;
  int rsize = -1;
  MPI_Comm_remote_size(kids, &rsize);
  if (rsize != 2) return 4;
  if (rank == 0) {
    for (int k = 0; k < 2; k++) {
      long v = 7 + k;
      MPI_Send(&v, 1, MPI_LONG, k, 40, kids);
    }
    for (int k = 0; k < 2; k++) {
      long got = -1;
      MPI_Recv(&got, 1, MPI_LONG, k, 41, kids, MPI_STATUS_IGNORE);
      if (got != (7 + k) * 10 + k) {
        fprintf(stderr, "child %d replied %ld\n", k, got);
        return 5;
      }
    }
  }
  MPI_Barrier(MPI_COMM_WORLD);
  printf("spawn_c rank %d/%d OK (2 children served)\n", rank, size);
  MPI_Finalize();
  return 0;
}
