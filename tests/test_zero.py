"""ZeRO-1 optimizer-state sharding over the DCN plane
(``parallel/zero.py``): 2 launcher slices with half batches each must
reproduce the single-process full-batch Adam trajectory exactly, while
each slice holds only half the optimizer state."""

import io
import os
import textwrap

import numpy as np

from zhpe_ompi_tpu.tools import mpirun

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_single_rank_matches_plain_adam():
    """size-1 degenerate: ZeroOptimizer == plain optax adam (with f32
    master arithmetic)."""
    import jax
    import jax.numpy as jnp
    import optax

    from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

    class OneProc:
        rank, size = 0, 1

    params = {"a": np.asarray([1.0, 2.0, 3.0], np.float32),
              "b": np.asarray([[4.0, 5.0]], np.float32)}
    grads = {"a": np.asarray([0.1, -0.2, 0.3], np.float32),
             "b": np.asarray([[0.5, -0.5]], np.float32)}
    z = ZeroOptimizer(OneProc(), optax.adam(1e-2), params)
    got = z.step(params, grads)

    opt = optax.adam(1e-2)
    st = opt.init(params)
    upd, st = opt.update(grads, st, params)
    want = optax.apply_updates(params, upd)
    for k in params:
        np.testing.assert_allclose(np.asarray(got[k]),
                                   np.asarray(want[k]), rtol=1e-6)


def test_two_slice_zero_matches_replicated_adam(tmp_path):
    prog = tmp_path / "zero.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {_REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.models import transformer as tfm
        from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

        proc = zmpi.host_init()
        cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                         n_layers=2, seq=8, dtype=jnp.float32)
        params = {{k: np.asarray(v) for k, v in
                  tfm.init_params(cfg, jax.random.PRNGKey(0)).items()}}
        r = np.random.default_rng(0)
        tok = r.integers(0, cfg.vocab, (8, cfg.seq))
        tgt = r.integers(0, cfg.vocab, (8, cfg.seq))
        lo, hi = proc.rank * 4, proc.rank * 4 + 4

        zopt = ZeroOptimizer(proc, optax.adam(1e-2), params)
        total = sum(v.size * 4 for v in params.values())
        # Adam state (mu + nu) for HALF the params on each slice
        sb = zopt.state_bytes()
        assert sb <= 2 * (total // 2 + 512), (sb, total)

        for _ in range(3):
            loss = lambda p: tfm.loss_fn(
                p, jnp.asarray(tok[lo:hi]), jnp.asarray(tgt[lo:hi]), cfg)
            grads = jax.grad(loss)(
                {{k: jnp.asarray(v) for k, v in params.items()}})
            params = zopt.step(params, grads)
        if proc.rank == 0:
            np.savez(os.path.join({str(tmp_path)!r}, "zero.npz"),
                     **{{k: np.asarray(v) for k, v in params.items()}})
            print("ZERO-DONE")
        proc.barrier()
        zmpi.host_finalize()
    """))
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(2, [str(prog)], stdout=out, stderr=err,
                       timeout=180.0)
    assert rc == 0, err.getvalue()
    assert "ZERO-DONE" in out.getvalue()

    # single-process full-batch reference with replicated adam (f32
    # master arithmetic like the zero path)
    import jax
    import jax.numpy as jnp
    import optax

    from zhpe_ompi_tpu.models import transformer as tfm

    cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                     n_layers=2, seq=8, dtype=jnp.float32)
    params = {k: np.asarray(v, np.float32) for k, v in
              tfm.init_params(cfg, jax.random.PRNGKey(0)).items()}
    r = np.random.default_rng(0)
    tok = r.integers(0, cfg.vocab, (8, cfg.seq))
    tgt = r.integers(0, cfg.vocab, (8, cfg.seq))
    opt = optax.adam(1e-2)
    st = opt.init(params)
    for _ in range(3):
        grads = jax.grad(lambda p: tfm.loss_fn(
            p, jnp.asarray(tok), jnp.asarray(tgt), cfg))(
            {k: jnp.asarray(v) for k, v in params.items()})
        grads = {k: np.asarray(v, np.float32) for k, v in grads.items()}
        upd, st = opt.update(grads, st, params)
        params = optax.apply_updates(params, upd)

    got = np.load(os.path.join(str(tmp_path), "zero.npz"))
    for k, v in params.items():
        np.testing.assert_allclose(got[k], np.asarray(v), rtol=3e-4,
                                   atol=3e-6)


def test_odd_bucket_partition_two_ranks():
    """Regression (round-4 review): a bucket whose size does not divide
    the world size must still update correctly — init and step share
    the padded chunk geometry."""
    import optax

    from test_tcp import run_tcp
    from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

    params = {"w": np.arange(5, dtype=np.float32)}
    g = np.full(5, 0.5, np.float32)

    def prog(p):
        z = ZeroOptimizer(p, optax.sgd(0.1), params)
        out = z.step(params, {"w": g})
        return np.asarray(out["w"])

    res = run_tcp(2, prog)
    want = params["w"] - 0.1 * 0.5  # mean of equal grads
    for r in range(2):
        np.testing.assert_allclose(res[r], want, rtol=1e-6)


def test_mismatched_tree_rejected():
    import optax
    import pytest

    from zhpe_ompi_tpu.core import errors
    from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

    class OneProc:
        rank, size = 0, 1

    z = ZeroOptimizer(OneProc(), optax.sgd(0.1),
                      {"w": np.zeros(8, np.float32)})
    with pytest.raises(errors.ArgError, match="sizes"):
        z.step({"w": np.zeros(8, np.float32)},
               {"w": np.zeros(4, np.float32)})
