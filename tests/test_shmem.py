"""OSHMEM-analog tests (reference: oshmem/, SURVEY.md §2.5) — the shape of
the reference's OpenSHMEM examples (examples/hello_oshmem_c.c,
oshmem_circular_shift.c, oshmem_symmetric_data.c, oshmem_strided_puts.c,
oshmem_max_reduction.c) as in-process acceptance tests."""

import numpy as np
import pytest

from zhpe_ompi_tpu import shmem
from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.shmem.memheap import ALIGN, SymmetricHeapAllocator

N = 4


@pytest.fixture()
def universe():
    return shmem.shmem_universe(N, heap_bytes=1 << 16)


class TestMemheap:
    def test_alloc_deterministic_and_aligned(self):
        a = SymmetricHeapAllocator(4096)
        b = SymmetricHeapAllocator(4096)
        offs_a = [a.alloc(10), a.alloc(100), a.alloc(64)]
        offs_b = [b.alloc(10), b.alloc(100), b.alloc(64)]
        assert offs_a == offs_b  # symmetric contract
        assert all(o % ALIGN == 0 for o in offs_a)

    def test_free_coalesce_reuse(self):
        a = SymmetricHeapAllocator(4096)
        o1 = a.alloc(64)
        o2 = a.alloc(64)
        a.free(o1)
        a.free(o2)
        assert a.alloc(128) == o1  # coalesced extent reused first-fit
        assert a.live_bytes == 128

    def test_exhaustion(self):
        a = SymmetricHeapAllocator(128)
        a.alloc(128)
        with pytest.raises(errors.ResourceError):
            a.alloc(1)


class TestPutGet:
    def test_circular_shift(self, universe):
        """oshmem_circular_shift_c analog: each PE puts its rank into its
        right neighbor's symmetric variable."""
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(1, np.int64)
            pe.local(sym)[...] = -1
            pe.barrier_all()
            pe.put(sym, pe.my_pe(), (pe.my_pe() + 1) % pe.n_pes())
            pe.barrier_all()
            return int(pe.local(sym)[0])

        results = uni.run(pe_main)
        assert results == [(r - 1) % N for r in range(N)]

    def test_p_g_single_element(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(8, np.float64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            # every PE writes its rank into slot rank of PE 0
            pe.p(sym, float(pe.my_pe() + 1), 0, index=pe.my_pe())
            pe.barrier_all()
            return pe.g(sym, 0, index=(pe.my_pe() + 1) % pe.n_pes())

        results = uni.run(pe_main)
        assert results == [float(((r + 1) % N) + 1) for r in range(N)]

    def test_strided_iput(self, universe):
        """oshmem_strided_puts_c analog."""
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(10, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            if pe.my_pe() == 0:
                pe.iput(sym, np.arange(5), 1, tst=2, sst=1)
            pe.barrier_all()
            return pe.local(sym).copy()

        results = uni.run(pe_main)
        expect = np.zeros(10, np.int64)
        expect[0:10:2] = np.arange(5)
        np.testing.assert_array_equal(results[1], expect)

    def test_exhaustion_raises_on_every_pe(self, universe):
        """Allocator failure must surface collectively — not deadlock the
        non-root PEs waiting on rank 0's offset broadcast."""
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            with pytest.raises(errors.ResourceError):
                pe.shmalloc(1 << 22, np.uint8)  # bigger than the heap
            ok = pe.shmalloc(8, np.int64)  # universe still usable after
            return ok.offset

        results = uni.run(pe_main)
        assert len(set(results)) == 1

    def test_iget_target_stride(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(8, np.int64)
            pe.local(sym)[...] = np.arange(8) + 10 * pe.my_pe()
            pe.barrier_all()
            target = np.zeros(8, np.int64)
            # fetch 3 elements of PE 1 at source stride 2, place at
            # target stride 3
            pe.iget(sym, pe=1, n=3, target=target, tst=3, sst=2)
            return target

        for t in uni.run(pe_main):
            np.testing.assert_array_equal(
                t, [10, 0, 0, 12, 0, 0, 14, 0]
            )

    def test_symmetric_free_and_realloc(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            s1 = pe.shmalloc(16, np.float32)
            off1 = s1.offset
            pe.shfree(s1)
            s2 = pe.shmalloc(16, np.float32)
            return (off1, s2.offset)

        results = uni.run(pe_main)
        assert all(r == results[0] for r in results)
        assert results[0][0] == results[0][1]  # freed space reused


class TestAtomics:
    def test_fetch_add_all_pes(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(1, np.int64)
            pe.local(sym)[...] = 0
            pe.barrier_all()
            olds = [pe.atomic_fetch_add(sym, 1, 0) for _ in range(100)]
            pe.barrier_all()
            return int(pe.local(sym)[0]), olds

        results = uni.run(pe_main)
        assert results[0][0] == N * 100  # no lost updates
        all_olds = sorted(o for _, olds in results for o in olds)
        assert all_olds == list(range(N * 100))  # each ticket unique

    def test_compare_swap(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(1, np.int64)
            pe.local(sym)[...] = -1
            pe.barrier_all()
            # every PE races to claim PE 0's slot; exactly one wins
            old = pe.atomic_compare_swap(sym, -1, pe.my_pe(), 0)
            pe.barrier_all()
            return int(old), int(pe.local(sym)[0]) if pe.my_pe() == 0 else None

        results = uni.run(pe_main)
        winners = [r for r, (old, _) in enumerate(results) if old == -1]
        assert len(winners) == 1
        assert results[0][1] == winners[0]

    def test_swap_and_set(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(1, np.float64)
            pe.local(sym)[...] = float(pe.my_pe())
            pe.barrier_all()
            if pe.my_pe() == 1:
                old = pe.atomic_swap(sym, 99.0, 0)
                assert old == 0.0
            pe.barrier_all()
            return float(pe.atomic_fetch(sym, 0))

        assert all(v == 99.0 for v in uni.run(pe_main))


class TestSync:
    def test_wait_until(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            flag = pe.shmalloc(1, np.int64)
            pe.local(flag)[...] = 0
            pe.barrier_all()
            if pe.my_pe() == 0:
                for r in range(1, pe.n_pes()):
                    pe.atomic_set(flag, 7, r)
                return 7
            pe.wait_until(flag, "eq", 7)
            return int(pe.local(flag)[0])

        assert uni.run(pe_main) == [7] * N

    def test_lock_mutual_exclusion(self, universe):
        uni, pes = universe
        counter = {"v": 0}

        def pe_main(ctx):
            pe = pes[ctx.rank]
            lock = pe.shmalloc(1, np.int64)
            for _ in range(50):
                pe.set_lock(lock)
                v = counter["v"]
                counter["v"] = v + 1
                pe.clear_lock(lock)
            pe.barrier_all()
            return counter["v"]

        results = uni.run(pe_main)
        assert results[0] == N * 50


class TestCollectives:
    def test_broadcast(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            sym = pe.shmalloc(4, np.float64)
            pe.local(sym)[...] = pe.my_pe()
            pe.barrier_all()
            pe.broadcast(sym, root=2)
            return pe.local(sym).copy()

        for r in uni.run(pe_main):
            np.testing.assert_array_equal(r, np.full(4, 2.0))

    def test_fcollect(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            src = pe.shmalloc(2, np.int64)
            dest = pe.shmalloc(2 * pe.n_pes(), np.int64)
            pe.local(src)[...] = [pe.my_pe() * 10, pe.my_pe() * 10 + 1]
            pe.barrier_all()
            pe.fcollect(dest, src)
            return pe.local(dest).copy()

        expect = np.array([v for r in range(N) for v in (r * 10, r * 10 + 1)])
        for r in uni.run(pe_main):
            np.testing.assert_array_equal(r, expect)

    def test_collect_ragged(self, universe):
        uni, pes = universe
        counts = [1, 3, 2, 1]

        def pe_main(ctx):
            pe = pes[ctx.rank]
            src = pe.shmalloc(3, np.int64)
            dest = pe.shmalloc(sum(counts), np.int64)
            pe.local(src)[...] = pe.my_pe() + 1
            pe.barrier_all()
            pe.collect(dest, src, counts)
            return pe.local(dest).copy()

        expect = np.concatenate(
            [np.full(counts[r], r + 1) for r in range(N)]
        )
        for r in uni.run(pe_main):
            np.testing.assert_array_equal(r, expect)

    def test_reductions(self, universe):
        """oshmem_max_reduction_c analog plus sum/prod."""
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            src = pe.shmalloc(3, np.int64)
            dmax = pe.shmalloc(3, np.int64)
            dsum = pe.shmalloc(3, np.int64)
            pe.local(src)[...] = [pe.my_pe(), -pe.my_pe(), 1]
            pe.barrier_all()
            pe.max_to_all(dmax, src)
            pe.sum_to_all(dsum, src)
            return pe.local(dmax).copy(), pe.local(dsum).copy()

        for mx, sm in uni.run(pe_main):
            np.testing.assert_array_equal(mx, [N - 1, 0, 1])
            np.testing.assert_array_equal(
                sm, [N * (N - 1) // 2, -N * (N - 1) // 2, N]
            )

    def test_alltoall(self, universe):
        uni, pes = universe

        def pe_main(ctx):
            pe = pes[ctx.rank]
            src = pe.shmalloc((N, 2), np.int64)
            dest = pe.shmalloc((N, 2), np.int64)
            for j in range(N):
                pe.local(src)[j] = [pe.my_pe(), j]
            pe.barrier_all()
            pe.alltoall(dest, src)
            return pe.local(dest).copy()

        results = uni.run(pe_main)
        for me, d in enumerate(results):
            for j in range(N):
                np.testing.assert_array_equal(d[j], [j, me])
