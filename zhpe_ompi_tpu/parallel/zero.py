"""ZeRO-1 across DCN slices: optimizer-state sharding over the host
plane.

The hybrid layer (``parallel/hybrid.py``) replicates optimizer state on
every slice; at scale that replication dominates memory.  ZeRO stage 1
partitions the flat parameter space across the data-parallel group so
each rank keeps optimizer state only for the 1/N partition it OWNS —
and the gradient synchronization becomes reduce-scatter (each owner
receives exactly its fully-reduced partition) followed by an allgather
of the updated parameters.  A ring allreduce IS a reduce-scatter plus an
allgather, so the wire bytes match plain DDP while the optimizer memory
drops by the slice count.

Framework-native composition: the partition runs on the SAME per-dtype
flat buckets ``pack_tree`` builds (bucketed like the gradient sync),
``proc.reduce_scatter`` / ``proc.allgather`` are the host-plane
collective algorithms, and extension float params (bf16/f8) ride the
lossless f32 transport — which doubles as f32 master weights: the
optimizer updates in f32 and the result casts back to the storage dtype
at unpack, exactly the mixed-precision recipe large trainers use.

Reference positioning: the reference has no optimizer (it is an MPI
library); this layer is the "distributed is first-class" composition
SURVEY §5's backend map calls for — the dp outer loop expressed in the
framework's own collectives.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import ops as zops
from ..core import errors
from ..runtime import ztrace
from .hybrid import pack_tree, unpack_tree


class ZeroOptimizer:
    """Stage-1 ZeRO over a host-plane endpoint (TcpProc across slices).

    ``optimizer`` is any optax GradientTransformation; its state exists
    only for this rank's partition of each flat dtype bucket.  ``step``
    takes the full (replicated) params tree and the LOCAL gradient tree
    and returns the updated full params tree — numpy leaves in the
    original dtypes, ready for ``jax.device_put``.
    """

    def __init__(self, proc, optimizer, params: Any,
                 weight: float | None = None):
        self.proc = proc
        self.optimizer = optimizer
        self.weight = weight
        buffers, self._treedef, self._meta = pack_tree(params)
        self._keys = sorted(buffers)
        self._sizes = {k: buffers[k].size for k in self._keys}
        # optimizer state over MY partition only, in the SAME padded
        # equal-chunk geometry step() reduces into (the padded tail of
        # the last rank carries zero state and its updates are
        # discarded at unpad) — f32 transport dtype = master precision
        my_chunks = {
            k: self._chunks_of(buffers[k].astype(np.float32),
                               k)[proc.rank].copy()
            for k in self._keys
        }
        self._opt_state = optimizer.init(my_chunks)

    def state_bytes(self) -> int:
        """Optimizer-state bytes held by THIS rank (the ZeRO saving)."""
        import jax

        return sum(
            getattr(leaf, "nbytes", 0)
            for leaf in jax.tree_util.tree_leaves(self._opt_state)
        )

    def _chunks_of(self, flat: np.ndarray, key: str) -> list[np.ndarray]:
        """Rank-indexed, padded-equal blocks of one flat bucket."""
        n = self.proc.size
        chunk = -(-self._sizes[key] // n)
        padded = np.zeros(chunk * n, np.float32)
        padded[: flat.size] = flat
        return [padded[r * chunk: (r + 1) * chunk] for r in range(n)]

    # -- re-sharding (the recovery pipeline's remesh step) ---------------

    def _bucket_of(self, path) -> str | None:
        """The flat-bucket key a state leaf belongs to, read off its
        tree path (optax preserves the ``{key: chunk}`` dict structure
        it was initialized with) — None for non-bucket leaves (step
        counts and other replicated scalars)."""
        for entry in reversed(path):
            key = getattr(entry, "key", None)
            if key in self._sizes:
                return key
        return None

    def full_state(self) -> Any:
        """The partitioned optimizer state gathered to FULL (unpadded
        flat f32 buckets) on every rank — the checkpointable form: a
        shrink-triggered rollback restores THIS, and :meth:`reshard`
        re-partitions it onto whatever endpoint survives.  Collective
        over the proc's whole group (one allgather per state leaf)."""
        import jax

        n = self.proc.size

        def gather(path, leaf):
            k = self._bucket_of(path)
            if k is None:
                return np.asarray(leaf)
            if n == 1:
                return np.asarray(leaf, np.float32)[: self._sizes[k]]
            parts = self.proc.allgather(np.asarray(leaf, np.float32))
            return np.concatenate(parts)[: self._sizes[k]]

        return jax.tree_util.tree_map_with_path(gather, self._opt_state)

    def reshard(self, proc, full_state: Any) -> None:
        """Re-partition onto a NEW endpoint — the survivor communicator
        of a shrink, or the full-size endpoint after respawn: adopt
        ``proc``'s size/rank as this optimizer's partition geometry and
        take this rank's chunk of every bucket leaf of ``full_state``
        (from :meth:`full_state` before the failure, or a checkpoint
        restore).  The padded-equal-chunk geometry is recomputed for
        the new size, so the SAME full state re-shards onto 3 survivors
        mid-recovery and back onto 4 ranks after the respawn."""
        import jax

        sp = ztrace.begin(ztrace.REMESH, getattr(proc, "rank", -1),
                          what="zero-opt") if ztrace.active else None
        self.proc = proc

        def scatter(path, leaf):
            k = self._bucket_of(path)
            if k is None:
                return np.asarray(leaf)
            full = np.zeros(self._sizes[k], np.float32)
            flat = np.asarray(leaf, np.float32).reshape(-1)
            full[: min(flat.size, full.size)] = flat[: full.size]
            return self._chunks_of(full, k)[proc.rank].copy()

        self._opt_state = jax.tree_util.tree_map_with_path(
            scatter, full_state)
        if sp is not None:
            sp.end(size=proc.size)

    def step(self, params: Any, grads: Any) -> Any:
        """One ZeRO-1 step: reduce-scatter grads, update the owned
        partition, allgather updated params.  Collective over the
        proc's whole group."""
        p_buf, p_tree, p_meta = pack_tree(params)
        g_buf, g_tree, _ = pack_tree(grads)
        for buf in (p_buf, g_buf):
            if {k: v.size for k, v in buf.items()} != self._sizes:
                raise errors.ArgError(
                    "params/grads buckets do not match the tree this "
                    "optimizer was built for (keys AND sizes must "
                    "agree)"
                )
        n, me = self.proc.size, self.proc.rank
        w = (1.0 / n) if self.weight is None else float(self.weight)
        new_chunks = {}
        my_updates = {}
        for k in self._keys:
            if n == 1:
                my_g = g_buf[k].astype(np.float32) * (
                    1.0 if self.weight is None else w)
                my_p = p_buf[k].astype(np.float32)
            else:
                blocks = self._chunks_of(
                    g_buf[k].astype(np.float32) * w, k)
                my_g = np.asarray(
                    self.proc.reduce_scatter(blocks, zops.SUM),
                    np.float32,
                )
                chunk = -(-self._sizes[k] // n)
                padded = np.zeros(chunk * n, np.float32)
                padded[: self._sizes[k]] = p_buf[k].astype(np.float32)
                my_p = padded[me * chunk: (me + 1) * chunk]
            my_updates[k] = (my_p, my_g)
        # one optax update over the owned-partition tree
        my_p_tree = {k: v[0] for k, v in my_updates.items()}
        my_g_tree = {k: v[1] for k, v in my_updates.items()}
        updates, self._opt_state = self.optimizer.update(
            my_g_tree, self._opt_state, my_p_tree
        )
        import optax

        new_local = optax.apply_updates(my_p_tree, updates)
        for k in self._keys:
            mine = np.asarray(new_local[k], np.float32)
            if n == 1:
                new_chunks[k] = mine[: self._sizes[k]]
            else:
                gathered = self.proc.allgather(mine)
                new_chunks[k] = np.concatenate(gathered)[: self._sizes[k]]
        return unpack_tree(new_chunks, p_tree, p_meta)
