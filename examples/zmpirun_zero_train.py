"""ZeRO-1 multi-slice training under the launcher: optimizer state is
PARTITIONED across slices (parallel/zero.py) — each process holds Adam
moments for 1/N of the flat parameter space, gradients reduce-scatter
so owners receive exactly their partition fully reduced, and updated
parameters allgather back.  Wire bytes match plain DDP; optimizer
memory drops by the slice count.

    python -m zhpe_ompi_tpu.tools.mpirun -n 2 examples/zmpirun_zero_train.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu.models import transformer as tfm
    from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

    proc = zmpi.host_init()
    cfg = tfm.Config(vocab=128, d_model=32, n_heads=4, d_ff=64,
                     n_layers=2, seq=16, dtype=jnp.float32)
    params = {k: np.asarray(v) for k, v in
              tfm.init_params(cfg, jax.random.PRNGKey(0)).items()}

    zopt = ZeroOptimizer(proc, optax.adam(1e-2), params)
    total_param_bytes = sum(v.nbytes for v in params.values())
    print(f"slice {proc.rank}: params {total_param_bytes}B, "
          f"my optimizer state {zopt.state_bytes()}B "
          f"(~1/{proc.size} of adam's 2x)")

    r = np.random.default_rng(proc.rank)  # each slice's own batch shard
    tok = jnp.asarray(r.integers(0, cfg.vocab, (4, cfg.seq)))
    tgt = jnp.asarray(r.integers(0, cfg.vocab, (4, cfg.seq)))
    losses = []
    for step_i in range(8):  # memorize one fixed batch per slice
        loss, grads = jax.value_and_grad(
            lambda p: tfm.loss_fn(p, tok, tgt, cfg)
        )({k: jnp.asarray(v) for k, v in params.items()})
        params = zopt.step(params, grads)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses  # it learns
    print(f"slice {proc.rank}: loss {losses[0]:.3f} -> {losses[-1]:.3f} "
          f"over 8 ZeRO steps — PASSED")
    proc.barrier()
    zmpi.host_finalize()


if __name__ == "__main__":
    main()
