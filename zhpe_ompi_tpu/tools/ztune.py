"""ztune — the self-tuning sweep harness of the collective decision plane.

The reference's coll/tuned earns its name by shipping decision tables
distilled from OSU benchmark sweeps; this tool closes that loop for the
analog.  It runs the ``benchmarks/osu_zmpi.py`` collective ladders per
emulated topology shape (flat / han2 / han3 — real processes with
``--real-procs``, the in-process thread harness by default), per op ×
per size × candidate algorithm, and distills the winners into a
sectioned dynamic-rules table (``coll/ztable.py`` format) keyed on
``(n_hosts, n_domains, ranks_per_domain)``.  ``--publish host:port``
pushes the table into a DVM's PMIx store under the well-known ztune key
(``runtime/pmix.py``), so every subsequent job launched on that DVM
resolves the tuned table for ITS topology at init with zero re-sweeping.

Selection is **counter-gated, not latency-gated**: the 1-CPU container
carries ±20% scheduler noise, so a candidate wins on its deterministic
wire deltas (``tcp_bytes_sent`` + ``sm_bytes_sent``, with the han phase
counters alongside) and the measured latency rides the emitted table as
report-only comment rows.  The distiller's regression gate enforces that
a table may NEVER pick an algorithm whose counter-gated wire bytes
exceed the stock auto decision's for that ``(op, comm_size, nbytes)``
cell — a planted worse-than-default winner moves
``tuned_regression_rejects``, never the table.

Verbs::

    python -m zhpe_ompi_tpu.tools.ztune --out tuned.table
    python -m zhpe_ompi_tpu.tools.ztune --out tuned.table --publish 127.0.0.1:7199
    python -m zhpe_ompi_tpu.tools.ztune --check tuned.table   # exit 0/1
"""

from __future__ import annotations

import json
import os
import sys
import time

#: emulated topology shapes: per-rank boot-id pins (host emulation) and
#: numa-id pins (domain emulation) exactly like the han bench ladder,
#: plus the (n_hosts, n_domains, ranks_per_domain) section key the
#: serving side derives from its own locality probe.
TOPOLOGIES = {
    "flat": {
        "boots": ("zthost0", "zthost1", "zthost2", "zthost3"),
        "numas": None,
        "key": (4, 4, 1),
        "hier": False,
    },
    "han2": {
        "boots": ("zthost0", "zthost0", "zthost1", "zthost1"),
        "numas": None,
        "key": (2, 2, 2),
        "hier": True,
    },
    "han3": {
        "boots": ("zthost0",) * 4 + ("zthost1",) * 4,
        "numas": ("ztd0", "ztd0", "ztd1", "ztd1") * 2,
        "key": (2, 4, 2),
        "hier": True,
    },
}

#: candidate algorithms per op — every name maps onto an eligibility-
#: guarded body behind coll/host.py's HOST_RULE_ALGS (or the han route);
#: crucially the set COVERS every choice the stock auto decision can
#: make, so the min-wire winner is never worse than auto and the
#: regression gate only ever fires on planted/corrupted cells.
CANDIDATES = {
    "allreduce": ("recursive_doubling", "ring", "han"),
    "reduce": ("binomial", "pipeline", "han"),
    "alltoall": ("pairwise", "bruck", "han"),
    "alltoallv": ("pairwise", "han"),
}

#: ops whose serve-time rules consult sees 0 payload bytes (per-rank
#: send lists are never congruent across ranks — the bcast discipline
#: in coll/host.py), so a rule with msg_bytes_min > 0 would be DEAD at
#: serve time: the distiller pins these ops' rules to bmin 0, electing
#: the winner from the smallest swept size (larger sizes ride the
#: report rows only — a size-split choice is not expressible).
SIZE_BLIND_OPS = frozenset(("alltoall", "alltoallv"))

#: counter deltas measured per cell: the first two are the gating wire
#: metric (sum = payload bytes that crossed a transport), the rest ride
#: the report for the han phase split.
CELL_COUNTERS = (
    "tcp_bytes_sent", "sm_bytes_sent",
    "coll_han_inter_bytes", "coll_han_intra_bytes",
    "coll_han_dleader_bytes", "sm_frag_sends",
)

_DEF_MIN_BYTES = 1 << 10
_DEF_MAX_BYTES = 64 << 10


def _wire(deltas: dict) -> int:
    return int(deltas.get("tcp_bytes_sent", 0)) \
        + int(deltas.get("sm_bytes_sent", 0))


# -- hygiene: no sweep worker may outlive its sweep ---------------------

_sweep_procs: list = []


def orphaned_sweep_processes() -> list[str]:
    """ztune sweep worker interpreters still alive — the conftest
    session gate's view (the dvm orphan-scan idiom): every ``--real-
    procs`` sweep owns killing its workers; ``--_worker`` children of a
    crashed parent are caught by the cmdline scan."""
    out = []
    for p in list(_sweep_procs):
        if p.poll() is None:
            out.append(f"ztune-worker pid {p.pid} (tracked)")
        else:
            _sweep_procs.remove(p)
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return out  # no /proc: nothing to scan
    for pid in pids:
        if int(pid) == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                args = [a.decode("utf-8", "replace")
                        for a in f.read().split(b"\0") if a]
        except OSError:
            continue  # raced an exit
        # match ACTUAL worker invocations only ("python -m
        # zhpe_ompi_tpu.tools.ztune --_worker ..."), never a shell or
        # pytest line that merely mentions ztune
        if any(a == "zhpe_ompi_tpu.tools.ztune" for a in args) \
                and "--_worker" in args:
            out.append(f"pid {pid}: {' '.join(args[:4])}...")
    return out


# -- measurement --------------------------------------------------------


def _osu():
    """The benchmark harness module; ``benchmarks/`` sits NEXT to the
    package, so a ``-m zhpe_ompi_tpu.tools.ztune`` run from anywhere
    needs the repo root on the path."""
    try:
        from benchmarks import osu_zmpi
    except ImportError:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from benchmarks import osu_zmpi
    return osu_zmpi


def _cell_body(proc, op: str, nbytes: int, iters: int, trials: int):
    """One rank's measurement body (thread AND real-process modes):
    correctness-checked warmup, then ``trials`` barrier-bracketed
    counter windows around ``iters`` calls; returns (min-wire counter
    deltas, best seconds/op).  The window is bracketed identically for
    every mode, so the deltas are comparable cell to cell."""
    import numpy as np

    from zhpe_ompi_tpu import ops as zops
    from zhpe_ompi_tpu.runtime import spc

    n, rank = proc.size, proc.rank
    arr = np.full(max(n, nbytes // 8), float(rank + 1), dtype=np.float64)
    expect = float(n * (n + 1) // 2)
    # alltoall family: nbytes total per rank, split into n per-
    # destination blocks stamped with the sender (correctness below)
    blocks = [np.full(max(1, nbytes // (8 * n)), float(rank + 1),
                      dtype=np.float64) for _ in range(n)]

    def run_once():
        if op == "allreduce":
            return proc.allreduce(arr, zops.SUM)
        if op == "alltoall":
            return proc.alltoall(list(blocks))
        if op == "alltoallv":
            return proc.alltoallv(np.concatenate(blocks),
                                  [b.size for b in blocks])
        return proc.reduce(arr, zops.SUM, 0)

    out = run_once()  # warmup + correctness (a tuned table must never
    if op in SIZE_BLIND_OPS:  # trade wrong answers for bytes)
        for src, blk in enumerate(out):
            got = np.asarray(blk).reshape(-1)
            if got[0] != float(src + 1) or got[-1] != float(src + 1):
                raise RuntimeError(
                    f"ztune cell {op}/{nbytes}B: wrong block from rank "
                    f"{src} (got {got[0]}, want {float(src + 1)})"
                )
    elif op == "allreduce" or rank == 0:
        got = np.asarray(out).reshape(-1)
        if got[0] != expect or got[-1] != expect:
            raise RuntimeError(
                f"ztune cell {op}/{nbytes}B: wrong result "
                f"(got {got[0]}, want {expect})"
            )
    best = None
    best_sec = float("inf")
    for _ in range(max(1, trials)):
        proc.barrier()
        base = {c: spc.read(c) for c in CELL_COUNTERS}
        proc.barrier()
        t0 = time.perf_counter()
        for _ in range(max(1, iters)):
            run_once()
        sec = (time.perf_counter() - t0) / max(1, iters)
        proc.barrier()
        deltas = {c: spc.read(c) - base[c] for c in CELL_COUNTERS}
        if best is None or _wire(deltas) < _wire(best):
            best = deltas
        best_sec = min(best_sec, sec)
    return best, best_sec


def _mode_vars(mode: str, alg: str | None, op: str,
               rules_path: str | None):
    """(var assignments) for a measurement mode: ``auto`` is the stock
    decision, ``flat`` the hand-set-constants path (han off, no rules
    — exactly the frozen defaults the sweep exists to beat), and
    ``rule:<alg>`` forces one candidate through a one-line table (the
    rules file is REWRITTEN IN PLACE per candidate — dogfooding the
    (mtime, size) cache invalidation this PR fixes)."""
    if mode == "flat":
        return {"coll_han_enable": "off", "coll_tuned_dynamic_rules": ""}
    if mode == "auto":
        return {"coll_han_enable": "auto",
                "coll_tuned_dynamic_rules": ""}
    assert alg is not None and rules_path is not None
    with open(rules_path, "w", encoding="utf-8") as fh:
        fh.write(f"{op} 0 0 {alg}\n")
    return {"coll_han_enable": "auto",
            "coll_tuned_dynamic_rules": rules_path}


def _measure_threads(topo: dict, op: str, nbytes: int, mode: str,
                     alg: str | None, rules_path: str | None,
                     iters: int, trials: int):
    """One (topology, op, size, mode) cell on the thread harness."""
    from zhpe_ompi_tpu.mca import var as mca_var

    osu = _osu()
    n = len(topo["boots"])
    kwargs_by_rank = {
        r: dict(
            sm_boot_id=topo["boots"][r],
            **({"sm_numa_id": topo["numas"][r]} if topo["numas"]
               else {}),
        )
        for r in range(n)
    }
    assigns = _mode_vars(mode, alg, op, rules_path)
    try:
        for name, value in assigns.items():
            mca_var.set_var(name, value)
        results = osu._run_tcp_ranks(
            n, lambda proc: _cell_body(proc, op, nbytes, iters, trials),
            timeout=300.0, sm=True, kwargs_by_rank=kwargs_by_rank,
        )
    finally:
        for name in assigns:
            mca_var.unset(name)
    # process-global counters: rank 0's barrier-bracketed window
    # already covers every rank's traffic
    deltas, sec = results[0]
    return deltas, sec


def _measure_procs(topo: dict, op: str, nbytes: int, mode: str,
                   alg: str | None, rules_path: str | None,
                   iters: int, trials: int):
    """The real-process twin: one interpreter per rank (own GIL, own
    counters — the parent sums the per-rank deltas), the osu port-
    reservation/drain/orphan-kill pattern, workers re-entering THIS
    module via ``--_worker``."""
    import socket
    import subprocess
    import threading

    osu = _osu()
    if mode.startswith("rule"):
        _mode_vars(mode, alg, op, rules_path)  # (re)write the table
    n = len(topo["boots"])
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = osu._bench_env(repo)
    last_exc = None
    for _attempt in range(3):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        procs = []
        try:
            for rank in range(n):
                spec = {
                    "rank": rank, "size": n, "port": port, "op": op,
                    "nbytes": nbytes, "iters": iters, "trials": trials,
                    "boot": topo["boots"][rank],
                    "numa": (topo["numas"][rank] if topo["numas"]
                             else None),
                    "mode": mode,
                    "rules_path": (rules_path
                                   if mode.startswith("rule") else None),
                }
                p = subprocess.Popen(
                    [sys.executable, "-m", "zhpe_ompi_tpu.tools.ztune",
                     "--_worker", json.dumps(spec)],
                    env=env, cwd=repo, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, text=True,
                )
                procs.append(p)
                _sweep_procs.append(p)
            outs: list = [None] * n
            errs: list = [None] * n

            def drain(rank, p):
                try:
                    outs[rank], errs[rank] = p.communicate(timeout=600)
                except subprocess.TimeoutExpired:
                    p.kill()
                    outs[rank], errs[rank] = p.communicate()

            threads = [threading.Thread(target=drain, args=(r, p),
                                        daemon=True)
                       for r, p in enumerate(procs)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for rank, p in enumerate(procs):
                if p.returncode != 0:
                    raise RuntimeError(
                        f"ztune worker rank {rank} failed:\n"
                        f"{errs[rank]}\n{outs[rank]}"
                    )
        finally:
            for p in procs:  # no orphan interpreters
                if p.poll() is None:
                    p.kill()
                    p.wait()
        try:
            reports = [json.loads(out.strip().splitlines()[-1])
                       for out in outs]
        except (ValueError, IndexError) as e:
            last_exc = RuntimeError(f"ztune worker report garbled: {e}")
            continue
        if any("Address already in use" in (e or "") for e in errs):
            last_exc = RuntimeError("coordinator port stolen (TOCTOU)")
            continue
        deltas = {c: sum(int(r["counters"].get(c, 0)) for r in reports)
                  for c in CELL_COUNTERS}
        sec = max(float(r["sec"]) for r in reports)
        return deltas, sec
    raise last_exc


def _worker_main(spec: dict) -> int:
    """``--_worker`` entry: one real-process sweep rank."""
    from zhpe_ompi_tpu.mca import var as mca_var
    from zhpe_ompi_tpu.pt2pt.tcp import TcpProc

    rank, n = int(spec["rank"]), int(spec["size"])
    if spec["mode"] == "flat":
        mca_var.set_var("coll_han_enable", "off")
    if spec.get("rules_path"):
        mca_var.set_var("coll_tuned_dynamic_rules", spec["rules_path"])
    proc = TcpProc(
        rank, n, coordinator=("127.0.0.1", int(spec["port"])),
        timeout=120.0, sm=True, sm_boot_id=spec.get("boot"),
        sm_numa_id=spec.get("numa"),
    )
    try:
        deltas, sec = _cell_body(
            proc, spec["op"], int(spec["nbytes"]), int(spec["iters"]),
            int(spec["trials"]),
        )
    finally:
        proc.close()
    print(json.dumps({"rank": rank, "counters": deltas, "sec": sec}),
          flush=True)
    return 0


# -- sweep + distill ----------------------------------------------------


def sweep(topos=("flat", "han2", "han3"),
          ops=("allreduce", "reduce", "alltoall", "alltoallv"),
          min_bytes: int = _DEF_MIN_BYTES,
          max_bytes: int = _DEF_MAX_BYTES, iters: int = 4,
          trials: int = 2, real_procs: bool = False,
          rules_path: str | None = None, progress=None) -> list[dict]:
    """Run the ladder: for every (topology, op, size) cell measure the
    stock ``auto`` decision, the hand-set-constants ``flat`` path, and
    every candidate algorithm; returns the raw cell list for
    :func:`distill`.  Counter-gated by construction — latency is
    carried report-only."""
    from zhpe_ompi_tpu.runtime import spc

    osu = _osu()
    measure = _measure_procs if real_procs else _measure_threads
    if rules_path is None:
        import tempfile

        fd, rules_path = tempfile.mkstemp(prefix="ztune_force_",
                                          suffix=".rules")
        os.close(fd)
    cells = []
    try:
        for tname in topos:
            topo = TOPOLOGIES[tname]
            n = len(topo["boots"])
            cands = {
                op: tuple(a for a in CANDIDATES[op]
                          if a != "han" or topo["hier"])
                for op in ops
            }
            for op in ops:
                for nbytes in osu._sizes(max_bytes, min_bytes):
                    cell = {
                        "topo": tname, "key": topo["key"], "op": op,
                        "comm_size": n, "nbytes": nbytes,
                        "modes": {},
                    }
                    runs = [("auto", None), ("flat", None)] + [
                        (f"rule:{a}", a) for a in cands[op]
                    ]
                    for mode, alg in runs:
                        deltas, sec = measure(
                            topo, op, nbytes, mode, alg, rules_path,
                            iters, trials,
                        )
                        cell["modes"][mode] = {
                            "wire": _wire(deltas),
                            "lat_us": sec * 1e6,
                            "counters": deltas,
                        }
                        spc.record("ztune_cells_swept")
                        if progress is not None:
                            progress(tname, op, nbytes, mode,
                                     cell["modes"][mode])
                    cells.append(cell)
    finally:
        try:
            os.unlink(rules_path)
        except OSError:
            pass
    return cells


def distill(cells: list[dict]) -> dict:
    """Distill swept cells into per-topology rules, enforcing the
    regression gate: the winner of a cell is its minimum-wire
    candidate, and a cell whose proposed winner moves MORE wire bytes
    than the stock auto decision is REJECTED loudly
    (``tuned_regression_rejects``) — the builtin decision keeps that
    cell.  A cell may carry ``"winner"`` explicitly (a planted or
    hand-edited table row); the gate applies identically.

    A cell whose winner falls to the gate (or that names an unswept
    winner) keeps the builtin decision — and if a neighboring cell
    already emitted a rule for the same op, the dropped cell gets an
    explicit ``builtin`` band terminator so the neighbor's rule can
    never leak over it (rules match by largest ``bmin`` <= payload).

    Returns ``{key: {"rules": [(op, cmin, bmin, alg)],
    "report": [...]}}`` with consecutive same-winner sizes merged."""
    from zhpe_ompi_tpu.mca import output as mca_output
    from zhpe_ompi_tpu.runtime import spc

    stream = mca_output.open_stream("ztune")
    out: dict = {}
    for cell in cells:
        key = tuple(cell["key"])
        modes = cell["modes"]
        auto = modes.get("auto")
        candidates = {
            m.split(":", 1)[1]: v for m, v in modes.items()
            if m.startswith("rule:")
        }
        winner = cell.get("winner")
        if winner is None:
            if not candidates:
                continue
            # deterministic order: wire, then tcp share, then name
            winner = min(
                candidates,
                key=lambda a: (candidates[a]["wire"],
                               candidates[a]["counters"].get(
                                   "tcp_bytes_sent", 0), a),
            )
        wdata = candidates.get(winner)
        alg = winner
        if wdata is None:
            mca_output.emit(
                stream,
                "ztune distill: cell %s/%s/%dB names unswept winner "
                "%r; the builtin decision keeps this cell",
                cell["topo"], cell["op"], cell["nbytes"], winner,
            )
            alg = "builtin"
        elif auto is not None and wdata["wire"] > auto["wire"]:
            # THE regression gate: a tuned table may never pick an
            # algorithm whose counter-gated wire bytes exceed the
            # default's for this (op, comm_size, nbytes) cell
            spc.record("tuned_regression_rejects")
            mca_output.emit(
                stream,
                "ztune distill: REJECTED %s/%s/%dB winner %r (%d wire "
                "bytes > auto default's %d); the builtin decision "
                "keeps this cell", cell["topo"], cell["op"],
                cell["nbytes"], winner, wdata["wire"], auto["wire"],
            )
            alg = "builtin"
        entry = out.setdefault(key, {"rules": [], "report": []})
        if alg != "builtin":
            entry["report"].append({
                "op": cell["op"], "nbytes": cell["nbytes"],
                "winner": winner, "wire": wdata["wire"],
                "auto_wire": auto["wire"] if auto else None,
                "flat_wire": (modes.get("flat") or {}).get("wire"),
                "lat_us": wdata.get("lat_us"),
            })
        rules = entry["rules"]
        op_rules = [r for r in rules if r[0] == cell["op"]]
        if cell["op"] in SIZE_BLIND_OPS:
            # serve-time consult sees 0 bytes: one bmin-0 rule per op,
            # elected by the smallest swept size (sweep order)
            if op_rules or alg == "builtin":
                continue
            rules.append((cell["op"], 0, 0, alg))
            continue
        # merge: only emit when the choice changes along the size axis;
        # a leading "builtin" is implicit (no rule = builtin)
        if op_rules and op_rules[-1][3] == alg:
            continue
        if not op_rules and alg == "builtin":
            continue
        rules.append((cell["op"], 0, cell["nbytes"], alg))
    return out


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def geometry_for(cells: list[dict], key: tuple) -> dict:
    """Per-class sm ring sizing from the sweep's working set (the PR 4
    leftover): rings sized to hold ~4 in-flight max-payload fragments
    instead of the frozen 4MB/2MB defaults — adopted by segment owners
    through pt2pt/sm.py's geometry path only while the vars are
    defaulted.  Clamped so tiny sweeps never starve the slot floor."""
    sizes = [c["nbytes"] for c in cells if tuple(c["key"]) == key]
    if not sizes:
        return {}
    biggest = max(sizes)
    ring = min(max(_next_pow2(4 * biggest), 256 << 10), 4 << 20)
    leader = min(max(_next_pow2(2 * biggest), 256 << 10), 2 << 20)
    return {"sm_ring_bytes": ring, "sm_leader_ring_bytes": leader}


def format_table(distilled: dict, geometry: dict | None = None,
                 note: str = "") -> str:
    """Render distilled rules as a coll/ztable.py sectioned table;
    latency and wire columns ride as comment rows (report-only — the
    counter gate picked the winners)."""
    lines = ["# ztune-generated tuned decision table"]
    if note:
        lines.append(f"# {note}")
    for key in sorted(distilled, key=lambda k: tuple(
            -1 if f is None else f for f in k)):
        entry = distilled[key]
        fields = " ".join("*" if f is None else str(f) for f in key)
        lines.append(f"[topology {fields}]")
        for rep in entry.get("report", []):
            lines.append(
                "#   %-10s %7dB -> %-18s wire=%s auto=%s flat=%s "
                "lat_us=%.1f (report-only)" % (
                    rep["op"], rep["nbytes"], rep["winner"],
                    rep["wire"], rep["auto_wire"], rep["flat_wire"],
                    rep["lat_us"] or 0.0,
                ))
        for op, cmin, bmin, alg in entry.get("rules", []):
            lines.append(f"{op} {cmin} {bmin} {alg}")
        for var, val in (geometry or {}).get(key, {}).items():
            lines.append(f"geometry {var} {val}")
    return "\n".join(lines) + "\n"


# -- verbs --------------------------------------------------------------


def check_table(path: str) -> int:
    """``--check``: strict validation of a table file — every line must
    parse (the serving side would degrade loudly per line; the check
    verb makes that degradation a FAILING exit for CI).  Exit 0/1."""
    from zhpe_ompi_tpu.coll import tuned  # installs the alg validator
    from zhpe_ompi_tpu.coll import ztable

    assert tuned._valid_rule_alg  # the validator import is the point
    try:
        with open(path, "r", encoding="utf-8") as fh:
            text = fh.read()
    except OSError as e:
        print(f"ztune --check: {path}: unreadable ({e})")
        return 1
    problems: list = []
    sections = ztable.parse_table(text, origin=path, problems=problems)
    for lineno, line, reason in problems:
        print(f"ztune --check: {path}:{lineno}: {line!r}: {reason}")
    nrules = sum(len(r) for _k, r, _g in sections)
    ngeom = sum(len(g) for _k, _r, g in sections)
    print(f"ztune --check: {path}: {len(sections)} section(s), "
          f"{nrules} rule(s), {ngeom} geometry line(s), "
          f"{len(problems)} problem(s)")
    return 1 if problems else 0


def publish(address: str, text: str) -> None:
    """Push a table into a live store (a zprted's PMIx port) under the
    well-known ztune key."""
    from zhpe_ompi_tpu.runtime import pmix as pmix_mod

    client = pmix_mod.PmixClient(address)
    try:
        pmix_mod.publish_tuned_table(client, text)
    finally:
        client.close()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="ztune",
        description="sweep collective ladders per topology, distill a "
                    "tuned decision table, publish it to a DVM store",
    )
    ap.add_argument("--check", metavar="TABLE",
                    help="validate TABLE strictly and exit 0/1")
    ap.add_argument("--out", metavar="FILE",
                    help="write the distilled table here")
    ap.add_argument("--publish", metavar="HOST:PORT",
                    help="publish the table into this PMIx store")
    ap.add_argument("--topos", default="flat,han2,han3")
    ap.add_argument("--ops", default="allreduce,reduce,alltoall,alltoallv")
    ap.add_argument("--min-bytes", type=int, default=_DEF_MIN_BYTES)
    ap.add_argument("--max-bytes", type=int, default=_DEF_MAX_BYTES)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--trials", type=int, default=2)
    ap.add_argument("--real-procs", action="store_true",
                    help="one interpreter per rank (the acceptance "
                         "topology); default is the thread harness")
    ap.add_argument("--no-geometry", action="store_true",
                    help="skip the sm ring-sizing lines")
    ap.add_argument("--_worker", help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args._worker:
        return _worker_main(json.loads(args._worker))
    if args.check:
        return check_table(args.check)

    topos = tuple(t for t in args.topos.split(",") if t)
    ops = tuple(o for o in args.ops.split(",") if o)
    for t in topos:
        if t not in TOPOLOGIES:
            print(f"ztune: unknown topology {t!r} "
                  f"(one of {', '.join(TOPOLOGIES)})")
            return 2
    for o in ops:
        if o not in CANDIDATES:
            print(f"ztune: unknown op {o!r} "
                  f"(one of {', '.join(CANDIDATES)})")
            return 2

    def progress(tname, op, nbytes, mode, data):
        print(f"ztune: {tname:5s} {op:10s} {nbytes:7d}B {mode:22s} "
              f"wire={data['wire']:<9d} lat_us={data['lat_us']:.1f}",
              flush=True)

    cells = sweep(
        topos=topos, ops=ops, min_bytes=args.min_bytes,
        max_bytes=args.max_bytes, iters=args.iters, trials=args.trials,
        real_procs=args.real_procs, progress=progress,
    )
    distilled = distill(cells)
    geometry = None
    if not args.no_geometry:
        geometry = {key: geometry_for(cells, key) for key in distilled}
    text = format_table(
        distilled, geometry,
        note=(f"swept {'real-process' if args.real_procs else 'thread'}"
              f" topologies={','.join(topos)} ops={','.join(ops)} "
              f"sizes=[{args.min_bytes},{args.max_bytes}]"),
    )
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"ztune: wrote {args.out}")
    else:
        sys.stdout.write(text)
    if args.publish:
        publish(args.publish, text)
        print(f"ztune: published to {args.publish}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
