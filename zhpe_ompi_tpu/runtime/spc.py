"""Software performance counters (SPC).

Re-design of ``ompi/runtime/ompi_spc.c`` (SURVEY.md §5): named monotonic
counters recorded at API call sites, surfaced through the MPI_T-style
introspection (zmpi-info) and resettable for tests/benchmarks.

Semantics note for a traced runtime: counters record **host-side events** —
under ``jit`` a collective is counted when traced (compiled), not per device
execution.  Eager calls count per call.  This is the honest analog on a
compile-once machine and is documented at the CLI.
"""

from __future__ import annotations

import threading
from collections import defaultdict

_counters: dict[str, int] = defaultdict(int)
_lock = threading.Lock()

WATERMARK = {"max_bytes_in_collective"}


def record(name: str, value: int = 1) -> None:
    with _lock:
        if name in WATERMARK:
            _counters[name] = max(_counters[name], value)
        else:
            _counters[name] += value


def read(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def snapshot() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def reset() -> None:
    with _lock:
        _counters.clear()
