"""zlint engine: file walking, suppressions, baseline, rule driving.

The engine is deliberately small: it parses every ``.py`` file once,
hands each parsed module to every registered rule (``visit``), then
lets cross-file rules reconcile their accumulated state (``finalize``
— the lock-order graph, the SPC doc-parity and MCA-registry-parity
audits need the whole scan set).  Findings carry a *stable key*
(path + rule + enclosing qualname + rule-specific detail, no line
numbers) so the checked-in baseline survives unrelated edits.
"""

from __future__ import annotations

import ast
import io
import os
import re
import sys
import tokenize
from dataclasses import dataclass, field

#: ``# zlint: disable=ZL001,ZL002 -- reason text`` (reason mandatory)
_SUPPRESS_RE = re.compile(
    r"#\s*zlint:\s*disable=([A-Za-z0-9,\s]+?)\s*(?:--\s*(\S.*))?$"
)

#: engine-level pseudo-rule id: parse errors and malformed suppressions
ENGINE_RULE = "ZL000"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # display path (as walked)
    path_key: str      # package-rooted stable path for the baseline
    line: int
    qualname: str      # enclosing Class.function scope ("<module>" at top)
    detail: str        # rule-specific stable fingerprint (no line numbers)
    message: str

    def key(self) -> str:
        """The baseline identity: stable across line-number drift."""
        return f"{self.path_key}|{self.rule}|{self.qualname}|{self.detail}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}: {self.rule} [{self.qualname}] "
            f"{self.message}"
        )


def _path_key(abspath: str) -> str:
    """Stable, location-independent identity for a scanned file: rooted
    at the last ``zhpe_ompi_tpu/`` package component when present (the
    real scan), else the basename (test fixtures in tmp dirs)."""
    norm = abspath.replace(os.sep, "/")
    marker = "/zhpe_ompi_tpu/"
    idx = norm.rfind(marker)
    if idx >= 0:
        return "zhpe_ompi_tpu/" + norm[idx + len(marker):]
    return os.path.basename(norm)


class Module:
    """One parsed file plus the lookups every rule needs."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.path_key = _path_key(os.path.abspath(path))
        self.src = src
        self.tree = ast.parse(src, filename=path)
        # suppressions: line -> set of rule ids; malformed ones (missing
        # the mandatory reason) recorded for the engine to flag
        self.suppress: dict[int, set[str]] = {}
        self.bad_suppressions: list[int] = []
        self._scan_comments()
        # parent links for qualname resolution
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.src).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                if not m.group(2):
                    # reason text is mandatory: a reasonless suppression
                    # is inert AND a finding
                    self.bad_suppressions.append(tok.start[0])
                    continue
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                self.suppress.setdefault(tok.start[0], set()).update(rules)
        except tokenize.TokenizeError:
            pass

    def qualname(self, node: ast.AST) -> str:
        parts: list[str] = []
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                parts.append(cur.name)
            cur = self._parents.get(cur)
        return ".".join(reversed(parts)) or "<module>"

    def enclosing_function(
        self, node: ast.AST
    ) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self._parents.get(cur)
        return None

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def is_suppressed(self, rule: str, line: int) -> bool:
        """A finding is suppressed by a comment on its own line or the
        line directly above (the statement-decoration idiom)."""
        for ln in (line, line - 1):
            rules = self.suppress.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False

    def finding(self, rule: str, node: ast.AST, detail: str, message: str
                ) -> Finding:
        return Finding(
            rule=rule, path=self.path, path_key=self.path_key,
            line=getattr(node, "lineno", 1), qualname=self.qualname(node),
            detail=detail, message=message,
        )


# -- shared AST helpers (used by the rules) ------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``self._rndv_lock`` / ``ch.lock`` / ``lock`` as text; None for
    anything that is not a plain name/attribute chain."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        if base is None:
            return None
        return f"{base}.{node.attr}"
    return None


def call_name(node: ast.Call) -> str | None:
    """The called function's LAST name component (``isend`` for both
    ``ep.isend(...)`` and ``isend(...)``)."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def call_receiver(node: ast.Call) -> str | None:
    """Dotted receiver of a method call (``mca_var`` for
    ``mca_var.get(...)``); None for bare-name calls."""
    if isinstance(node.func, ast.Attribute):
        return dotted_name(node.func.value)
    return None


_UNFOLDABLE = object()


def const_fold(node: ast.AST, mod: Module | None = None):
    """Fold a constant expression (``64 * 1024``, ``128 << 10``,
    ``-1``, tuples of constants); resolves one hop of module-level
    ``NAME = <const>`` assignments when ``mod`` is given.  Returns
    the value or the ``UNFOLDABLE`` sentinel."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(
        node.op, (ast.USub, ast.UAdd, ast.Invert)
    ):
        v = const_fold(node.operand, mod)
        if v is _UNFOLDABLE:
            return _UNFOLDABLE
        try:
            if isinstance(node.op, ast.USub):
                return -v
            if isinstance(node.op, ast.UAdd):
                return +v
            return ~v
        except TypeError:
            return _UNFOLDABLE
    if isinstance(node, ast.BinOp):
        lv = const_fold(node.left, mod)
        rv = const_fold(node.right, mod)
        if lv is _UNFOLDABLE or rv is _UNFOLDABLE:
            return _UNFOLDABLE
        try:
            if isinstance(node.op, ast.Add):
                return lv + rv
            if isinstance(node.op, ast.Sub):
                return lv - rv
            if isinstance(node.op, ast.Mult):
                return lv * rv
            if isinstance(node.op, ast.Div):
                return lv / rv
            if isinstance(node.op, ast.FloorDiv):
                return lv // rv
            if isinstance(node.op, ast.Mod):
                return lv % rv
            if isinstance(node.op, ast.Pow):
                return lv ** rv
            if isinstance(node.op, ast.LShift):
                return lv << rv
            if isinstance(node.op, ast.RShift):
                return lv >> rv
            if isinstance(node.op, ast.BitOr):
                return lv | rv
            if isinstance(node.op, ast.BitAnd):
                return lv & rv
        except (TypeError, ValueError, ZeroDivisionError):
            return _UNFOLDABLE
        return _UNFOLDABLE
    if isinstance(node, ast.Tuple):
        vals = [const_fold(e, mod) for e in node.elts]
        if any(v is _UNFOLDABLE for v in vals):
            return _UNFOLDABLE
        return tuple(vals)
    if isinstance(node, ast.Name) and mod is not None:
        # one-hop module-level constant (``_DEFAULT_SMALL = 8192``)
        for stmt in mod.tree.body:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == node.id:
                        return const_fold(stmt.value, None)
        return _UNFOLDABLE
    return _UNFOLDABLE


const_fold.UNFOLDABLE = _UNFOLDABLE  # type: ignore[attr-defined]


# -- baseline ------------------------------------------------------------


def load_baseline(path: str) -> dict[str, str]:
    """``key -- justification`` per line; '#' comments and blanks
    ignored.  Returns key -> justification."""
    entries: dict[str, str] = {}
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for raw in fh:
                line = raw.strip()
                if not line or line.startswith("#"):
                    continue
                key, sep, reason = line.partition(" -- ")
                if not sep or not reason.strip():
                    # a baseline entry without a justification does not
                    # grandfather anything
                    continue
                entries[key.strip()] = reason.strip()
    except OSError:
        pass
    return entries


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.txt")


# -- runner --------------------------------------------------------------


def _walk_py(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
        elif os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d != "__pycache__" and not d.startswith(".")
                )
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.join(root, name))
    return files


@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)
    suppressed: int = 0
    baselined: int = 0
    stale_baseline: list[str] = field(default_factory=list)
    files: int = 0

    @property
    def clean(self) -> bool:
        return not self.findings


def lint_paths(paths: list[str], baseline: str | None = None,
               rules=None) -> LintResult:
    """Lint files/dirs; returns surviving findings (suppressions and
    the baseline already applied).  ``rules`` defaults to the full
    registry (``rules.all_rules()``)."""
    if rules is None:
        from .rules import all_rules
        rules = all_rules()
    result = LintResult()
    modules: list[Module] = []
    raw: list[Finding] = []
    walked = _walk_py(paths)
    result.files = len(walked)
    for path in walked:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            mod = Module(path, src)
        except (OSError, SyntaxError, ValueError) as e:
            raw.append(Finding(
                rule=ENGINE_RULE, path=path,
                path_key=_path_key(os.path.abspath(path)), line=1,
                qualname="<module>", detail="parse-error",
                message=f"cannot parse: {e}",
            ))
            continue
        modules.append(mod)
        for idx, line in enumerate(mod.bad_suppressions, 1):
            raw.append(Finding(
                rule=ENGINE_RULE, path=path, path_key=mod.path_key,
                line=line, qualname="<module>",
                # occurrence ordinal, NOT the line number: baseline
                # keys must survive line drift like every other rule's
                detail=f"reasonless-suppression:{idx}",
                message="suppression without the mandatory reason text "
                        "(`# zlint: disable=RULE -- reason`); ignored",
            ))
        for rule in rules:
            raw.extend(rule.visit(mod))
    for rule in rules:
        raw.extend(rule.finalize(modules))

    by_path = {m.path: m for m in modules}
    entries = load_baseline(baseline) if baseline else {}
    used: set[str] = set()
    for f in raw:
        mod = by_path.get(f.path)
        if mod is not None and f.rule != ENGINE_RULE \
                and mod.is_suppressed(f.rule, f.line):
            result.suppressed += 1
            continue
        if f.key() in entries:
            used.add(f.key())
            result.baselined += 1
            continue
        result.findings.append(f)
    result.stale_baseline = sorted(set(entries) - used)
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return result


def run(paths: list[str], baseline: str | None = None,
        out=None) -> int:
    """CLI body: print findings, return the exit code (0 clean, 1
    findings, 2 nothing scanned)."""
    out = out or sys.stdout
    result = lint_paths(paths, baseline=baseline)
    if result.files == 0:
        print("zlint: no Python files found", file=out)
        return 2
    for f in result.findings:
        print(f.render(), file=out)
    for key in result.stale_baseline:
        print(f"zlint: stale baseline entry (no longer found): {key}",
              file=out)
    print(
        f"zlint: {result.files} files, {len(result.findings)} finding(s), "
        f"{result.suppressed} suppressed, {result.baselined} baselined",
        file=out,
    )
    return 1 if result.findings else 0
