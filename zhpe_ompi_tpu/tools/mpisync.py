"""mpisync — cross-rank clock offset estimation for trace alignment.

Re-design of ``ompi/tools/mpisync`` (SURVEY.md §2.6): the reference
measures per-node clock offsets against rank 0 so that tool timestamps
(PERUSE events, ztrace spans, monitoring dumps) from different nodes can
be merged on one timeline.  Same algorithm here: for each rank, rank 0
runs a burst of ping-pong exchanges, the offset estimate is ``theta =
t_peer − (t0_send + rtt/2)`` from the minimum-RTT sample (the classic
Cristian/NTP estimator the reference uses — its README cites the same
approach).

Protocol: both sides know ``rounds``, so the exchange is fully
deterministic BLOCKING recvs — rank 0 sends, the peer's blocking recv
wakes, the peer answers with its clock, exactly ``rounds`` times per
peer.  (The original shape was a ``probe`` + ``sleep(0)`` polling
server; besides burning a core, every scheduler quantum the spinner
stole inflated the very RTT the estimator minimizes.)

Runs on BOTH planes: pass a :class:`~zhpe_ompi_tpu.pt2pt.universe.
LocalUniverse` and it launches the thread ranks itself (the original
surface), or call it COLLECTIVELY on real-process endpoints
(``TcpProc`` — every rank of the job calls ``sync_clocks(ep)``; rank 0
returns the offsets, the others return None).  Thread-ranks share one
clock, so the *measured* offset is ~0; tests inject synthetic skew
through the ``clock`` hook — which is also how ``tools/ztrace`` plugs
each process's wall-anchored trace clock in
(:func:`zhpe_ompi_tpu.runtime.ztrace.trace_clock`).
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from ..pt2pt.universe import LocalUniverse

_SYNC_TAG = 0x51C
_SYNC_CID = 0x51C


def _sync_body(ctx, rounds: int,
               clock: Callable[[int], float]) -> list[float] | None:
    """The collective body: rank 0 measures every peer with
    ``rounds`` ping-pongs; peers serve exactly ``rounds`` blocking
    recv→answer exchanges.  No probe, no polling, no release frame —
    both sides know the round count."""
    if ctx.rank == 0:
        offsets = [0.0]
        for peer in range(1, ctx.size):
            best_rtt = np.inf
            best_theta = 0.0
            for _ in range(rounds):
                t0 = clock(0)
                ctx.send(t0, dest=peer, tag=_SYNC_TAG, cid=_SYNC_CID)
                t_peer = ctx.recv(
                    source=peer, tag=_SYNC_TAG, cid=_SYNC_CID
                )
                t1 = clock(0)
                rtt = t1 - t0
                if rtt < best_rtt:
                    best_rtt = rtt
                    best_theta = t_peer - (t0 + rtt / 2.0)
            offsets.append(best_theta)
        return offsets
    for _ in range(rounds):
        ctx.recv(source=0, tag=_SYNC_TAG, cid=_SYNC_CID)
        ctx.send(clock(ctx.rank), dest=0, tag=_SYNC_TAG, cid=_SYNC_CID)
    return None


def sync_clocks(uni_or_ep, rounds: int = 16,
                clock: Callable[[int], float] | None = None
                ) -> list[float] | None:
    """Estimated clock offset of every rank relative to rank 0
    (seconds).

    Accepts a ``LocalUniverse`` (runs the thread ranks itself and
    returns rank 0's offsets — the original surface) OR any endpoint
    with ``rank``/``size``/``send``/``recv`` (``TcpProc``,
    ``RankContext``): then it is a COLLECTIVE — every rank calls it,
    rank 0 returns the offsets list, the rest return None.

    ``clock(rank)`` returns that rank's notion of "now" (defaults to
    the shared monotonic clock; a real-process caller passes its OWN
    clock — e.g. ``lambda r: ztrace.trace_clock()`` — the per-process
    domain the offsets are measured between)."""
    if clock is None:
        clock = lambda rank: time.monotonic()  # noqa: E731
    if isinstance(uni_or_ep, LocalUniverse):
        results = uni_or_ep.run(
            lambda ctx: _sync_body(ctx, rounds, clock))
        return results[0]
    return _sync_body(uni_or_ep, rounds, clock)


def _run_tcp_plane(n: int, skew: list[float], rounds: int
                   ) -> list[float]:  # pragma: no cover - CLI harness
    """CLI ``--plane tcp``: N real-socket ranks over loopback (threads
    hosting TcpProc endpoints), the collective sync over the wire."""
    import socket
    import threading

    from ..pt2pt.tcp import TcpProc

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs: list = [None] * n
    out: list = [None] * n
    errs: list = []

    def body(r):
        try:
            procs[r] = TcpProc(r, n, coordinator=("127.0.0.1", port))
            out[r] = sync_clocks(
                procs[r], rounds=rounds,
                clock=lambda rank, r=r: time.monotonic() + skew[r],
            )
        except Exception as e:  # noqa: BLE001 - reported below
            errs.append((r, e))
        finally:
            if procs[r] is not None:
                procs[r].close()

    threads = [threading.Thread(target=body, args=(r,), daemon=True)
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errs:
        raise RuntimeError(f"tcp sync failed: {errs}")
    return out[0]


def main(argv: list[str] | None = None) -> int:  # pragma: no cover - CLI
    import argparse

    p = argparse.ArgumentParser(description="clock-sync demo (mpisync analog)")
    p.add_argument("-n", "--ranks", type=int, default=4)
    p.add_argument("--rounds", type=int, default=16)
    p.add_argument("--plane", choices=("threads", "tcp"),
                   default="threads",
                   help="threads = LocalUniverse thread ranks (shared "
                        "clock); tcp = real-socket TcpProc endpoints "
                        "over loopback")
    p.add_argument("--skew", type=float, nargs="*", default=None,
                   help="per-rank synthetic skew seconds")
    args = p.parse_args(argv)
    skew = args.skew or [0.0] * args.ranks
    if args.plane == "tcp":
        offsets = _run_tcp_plane(args.ranks, skew, args.rounds)
    else:
        uni = LocalUniverse(args.ranks)
        offsets = sync_clocks(
            uni, rounds=args.rounds,
            clock=lambda r: time.monotonic() + skew[r],
        )
    for r, off in enumerate(offsets):
        print(f"rank {r}: offset {off * 1e6:+.1f} us")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
