"""Multi-slice training under the launcher: each OS process is a
"slice" running the optax train step on its own batch shard; gradients
sync across slices over the host plane (DCN) between the two jits.

    python -m zhpe_ompi_tpu.tools.mpirun -n 2 examples/zmpirun_multislice_train.py

On TPU pods each slice would own an ICI mesh (dp/tp/sp inside); here
each slice is one CPU device, which exercises the identical code path.
"""

import os
import sys


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    import numpy as np

    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu.models import transformer as tfm

    proc = zmpi.host_init()
    cfg = tfm.Config(vocab=128, d_model=32, n_heads=4, d_ff=64,
                     n_layers=2, seq=16, dtype=jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1), ("dp", "tp"))
    dp_comm = zmpi.Communicator(mesh, "dp")
    init_state, step, specs = tfm.make_train_step_optax(
        cfg, mesh, dp_comm, None, optimizer=optax.adam(1e-2),
        dcn_proc=proc,
    )
    params = {
        k: jax.device_put(np.asarray(v), NamedSharding(mesh, specs[k]))
        for k, v in tfm.init_params(cfg, jax.random.PRNGKey(0)).items()
    }
    st = init_state(params)
    r = np.random.default_rng(proc.rank)  # per-slice data shard
    ds = NamedSharding(mesh, P("dp"))
    tok = jax.device_put(jnp.asarray(r.integers(0, cfg.vocab, (4, cfg.seq))), ds)
    tgt = jax.device_put(jnp.asarray(r.integers(0, cfg.vocab, (4, cfg.seq))), ds)

    losses = []
    for s in range(5):
        params, st, loss = step(params, st, tok, tgt)
        losses.append(float(loss))
    # slices must agree bit-for-bit after DCN-synced updates
    digest = float(sum(np.abs(np.asarray(v)).sum() for v in params.values()))
    all_digests = proc.allgather(digest)
    if max(all_digests) - min(all_digests) > 1e-9:
        print(f"rank {proc.rank}: slices diverged: {all_digests}")
        sys.exit(1)
    ok = losses[-1] < losses[0]
    if proc.rank == 0:
        print(f"{proc.size} slices, losses {[round(x, 3) for x in losses]}")
        if ok:
            print("PASSED")
    zmpi.host_finalize()  # teardown first; exit code after
    if proc.rank == 0 and not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
