"""Collective framework: components (tpu/tuned/basic/han) + algorithm
library.  ``han`` (the hierarchical host component) loads lazily — it
pulls the pt2pt group machinery, which most device-plane users never
touch."""
import importlib

from . import algorithms, framework

__all__ = ["algorithms", "framework", "han"]


def __getattr__(name):
    # PEP 562; importlib directly, not `from . import` — the fromlist
    # path re-enters this hook before the submodule lands in sys.modules
    if name == "han":
        return importlib.import_module(".han", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
