"""SPMD-plane point-to-point primitives.

The TPU-native answer to the reference's BTL send/recv
(``opal/mca/btl/btl.h:901``): on an SPMD machine a *static communication
pattern* is one XLA ``collective_permute`` riding ICI — there is no
per-message matching, no eager/rendezvous split, no progress engine.  The
dynamic-tag-matching MPI semantics live in the host plane
(:mod:`zhpe_ompi_tpu.pt2pt.matching`); every collective algorithm in
:mod:`zhpe_ompi_tpu.coll` bottoms out here, the way the reference's
collectives bottom out in ``MCA_PML_CALL(send/recv)``
(``coll_base_util.h:70-98``).

All rank arguments are comm-relative; translation to mesh axis indices goes
through the communicator's partition.  Patterns are instantiated per
sub-group (a callable receives each group's size), so one XLA op carries the
pattern for every sub-communicator of a split simultaneously.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax

from ..core import errors

PatternFn = Callable[[int], Sequence[tuple[int, int]]]


def global_pairs(comm, pattern: Sequence[tuple[int, int]] | PatternFn
                 ) -> list[tuple[int, int]]:
    """Translate a comm-relative pattern to mesh-axis-index pairs across every
    sub-group.  `pattern` is either an explicit pair list (applied to each
    group; pairs exceeding a group's size are dropped) or a callable
    ``group_size -> pairs`` for size-dependent patterns (ring wrap etc.)."""
    out: list[tuple[int, int]] = []
    seen_dst: set[int] = set()
    for g in comm.partition:
        pairs = pattern(g.size) if callable(pattern) else pattern
        for s, d in pairs:
            if s >= g.size or d >= g.size:
                continue
            gs, gd = g.ranks[s], g.ranks[d]
            if gd in seen_dst:
                raise errors.ArgError(
                    f"duplicate destination {gd} in permute pattern"
                )
            seen_dst.add(gd)
            out.append((gs, gd))
    return out


def ppermute(comm, x, pattern: Sequence[tuple[int, int]] | PatternFn):
    """Collective permute with comm-relative static pattern.

    Ranks that are not a destination receive zeros (XLA collective_permute
    semantics — algorithms mask with ``jnp.where``).
    """
    return jax.lax.ppermute(x, comm.axis, perm=global_pairs(comm, pattern))


def shift(comm, x, offset: int, wrap: bool = True):
    """Send to (rank+offset) mod group_size — the ring primitive.

    With ``wrap=False`` the ends don't exchange (MPI_PROC_NULL semantics of
    MPI_Cart_shift with a non-periodic topology): falling-off ranks receive
    zeros.
    """

    def pattern(n: int):
        ps = []
        for i in range(n):
            j = i + offset
            if wrap:
                ps.append((i, j % n))
            elif 0 <= j < n:
                ps.append((i, j))
        return ps

    return ppermute(comm, x, pattern)


def sendrecv_shift(comm, x, offset: int):
    """ompi_coll_base_sendrecv analog for the uniform-shift pattern."""
    return shift(comm, x, offset, wrap=True)


def sendrecv(comm, x, dest_of: list[int]):
    """Fully general static sendrecv: `dest_of[i]` is where comm rank i's
    buffer goes (use -1 for "sends nowhere")."""
    pairs = [(i, d) for i, d in enumerate(dest_of) if d >= 0]
    return ppermute(comm, x, pairs)
