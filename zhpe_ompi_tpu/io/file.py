"""MPI_File over the datatype engine.

Reference anatomy: ``ompi/mca/io/ompio/io_ompio_file_open.c`` (open/modes),
``common_ompio_file_view.c`` (the (disp, etype, filetype) view decode),
``common_ompio_file_read/write.c`` (individual IO through the convertor),
``fcoll/two_phase`` (collective aggregation), ``sharedfp/lockedfile``
(shared pointer).  This module re-designs all four for a single-controller
machine:

- The view's filetype tiles across the file; element byte offsets come from
  the SAME ``byte_index_map`` the message convertor uses — one engine for
  wire and disk, as OMPIO reuses ``opal_convertor``.
- Per-rank individual file pointers and per-rank views live in one File
  object (the controller holds all ranks).
- Collective write_all/read_all computes every rank's (offset, length)
  runs, sorts and coalesces adjacent extents, then issues few large
  pread/pwrite calls — the two-phase optimization without the exchange
  phase (no inter-process data movement exists to optimize away).
- The shared file pointer is an integer under a lock (sharedfp/sm analog).
"""

from __future__ import annotations

import os
import threading

import numpy as np

from ..core import errhandler
from ..core import errors
from ..datatype import convertor
from ..datatype.predefined import BYTE, Datatype
from . import fs as fs_mod

MODE_RDONLY = 0x01
MODE_RDWR = 0x02
MODE_WRONLY = 0x04
MODE_CREATE = 0x08
MODE_EXCL = 0x10
MODE_APPEND = 0x20
MODE_DELETE_ON_CLOSE = 0x40


def _os_flags(mode: int) -> int:
    rw = mode & (MODE_RDONLY | MODE_RDWR | MODE_WRONLY)
    if rw == MODE_RDONLY:
        flags = os.O_RDONLY
    elif rw == MODE_WRONLY:
        flags = os.O_WRONLY
    elif rw == MODE_RDWR:
        flags = os.O_RDWR
    else:
        raise errors.ArgError("exactly one of RDONLY/RDWR/WRONLY required")
    if mode & MODE_CREATE:
        flags |= os.O_CREAT
    if mode & MODE_EXCL:
        flags |= os.O_EXCL
    # MODE_APPEND deliberately does NOT map to O_APPEND: Linux pwrite on an
    # O_APPEND fd ignores its offset, which would corrupt every view-computed
    # write.  MPI_MODE_APPEND means "file pointers start at EOF" — handled
    # in File.__init__.
    return flags


class _View:
    """One rank's (disp, etype, filetype) triple, pre-decoded into the
    byte positions of one filetype tile (common_ompio_file_view.c)."""

    def __init__(self, disp: int, etype: Datatype, filetype: Datatype):
        if filetype.size % max(etype.size, 1) != 0:
            raise errors.TypeError_(
                f"filetype size {filetype.size} is not a multiple of etype "
                f"size {etype.size}"
            )
        self.disp = disp
        self.etype = etype
        self.filetype = filetype
        self.etypes_per_tile = filetype.size // etype.size if etype.size else 0
        # byte positions of one tile's accessible bytes, in pack order
        self.tile_positions = convertor.byte_index_map(filetype, 1)
        self.tile_extent = filetype.extent

    def byte_offsets(self, start_etype: int, count: int) -> np.ndarray:
        """Absolute file byte offsets for `count` etypes starting at etype
        index `start_etype` (int64 array of count*etype.size entries)."""
        esz = self.etype.size
        if count == 0 or esz == 0:
            return np.empty(0, dtype=np.int64)
        e = np.arange(start_etype, start_etype + count, dtype=np.int64)
        tiles = e // self.etypes_per_tile
        within = e % self.etypes_per_tile
        segs = self.tile_positions.reshape(self.etypes_per_tile, esz)
        return (
            self.disp + tiles[:, None] * self.tile_extent + segs[within]
        ).ravel()


class _MappedRequest:
    """Framework request surface (wait/test/done) over an async fbtl
    transfer, with a completion transform applied on the waiter's thread
    (typed view for reads, etype count for writes)."""

    def __init__(self, inner, fn):
        self._inner = inner
        self._fn = fn

    @property
    def done(self) -> bool:
        return self._inner.done

    def test(self):
        flag, value = self._inner.test()
        return (True, self._fn(value)) if flag else (False, None)

    def wait(self, timeout: float | None = None):
        return self._fn(self._inner.wait(timeout))


# Shared nonblocking engine for File and WireFile (MPI_File_iread/iwrite
# over the async fbtl; reference ompi/mpi/c/file_iwrite.c:38 +
# fbtl_posix_ipreadv.c): the SAME MCA-selected fcoll strategy the
# blocking path uses, submitted to the worker pool — one
# sort/coalesce/unpermute engine for both paths.

def iread_offsets(async_fbtl, fcoll, fbtl, fd: int, offsets: np.ndarray,
                  np_dtype):
    inner = async_fbtl.submit(
        lambda: fcoll.read(fbtl, fd, [offsets])[0])

    def fn(raw):
        return raw.view(np_dtype) if np_dtype is not None else raw

    return _MappedRequest(inner, fn)


def iwrite_offsets(async_fbtl, fcoll, fbtl, fd: int, offsets: np.ndarray,
                   data: np.ndarray, count: int):
    # defensive copy: the worker reads `data` later, after this call has
    # returned — the caller is free to reuse its buffer immediately
    data = data.copy()
    inner = async_fbtl.submit(
        lambda: fcoll.write(fbtl, fd, [(offsets, data)]))
    return _MappedRequest(inner, lambda _nbytes: count)


class File(errhandler.HasErrhandler):
    """MPI_File analog; one object serves every rank of `comm`.

    Accepts an MPI_Info of hints (MPI_File_open's info argument); files
    default to MPI_ERRORS_RETURN (the reference's file default)."""

    _default_errhandler = errhandler.ERRORS_RETURN

    def __init__(self, comm, path: str, mode: int = MODE_RDONLY,
                 info=None):
        from ..core import info as info_mod

        self.comm = comm
        self.path = path
        self.mode = mode
        self.info = info_mod.coerce(info)
        self.name = f"file:{path}"
        self._fs = fs_mod.select_fs()
        from . import fbtl as fbtl_mod
        from . import fcoll as fcoll_mod

        self._fbtl = fbtl_mod.select_fbtl()
        self._fcoll = fcoll_mod.select_fcoll()
        self._fd = self._fs.open(path, _os_flags(mode))
        n = comm.size if comm is not None else 1
        self._views = [_View(0, BYTE, BYTE) for _ in range(n)]
        # MPI_MODE_APPEND: all pointers start at EOF (etype = BYTE at open)
        start = self._fs.size(self._fd) if mode & MODE_APPEND else 0
        self._pointers = [start] * n  # individual, in etype units
        self._shared = start  # shared pointer, etype units of rank-0's view
        self._lock = threading.Lock()
        self._closed = False

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if not self._closed:
            # quiesce in-flight nonblocking IO first: closing the fd
            # under an async transfer would let a recycled fd number
            # receive the stale write (the reference completes pending
            # aio before the fd dies)
            if hasattr(self, "_ifbtl"):
                self._ifbtl.close()
            self._fs.close(self._fd)
            self._closed = True
            if self.mode & MODE_DELETE_ON_CLOSE:
                self._fs.delete(self.path)

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _check_open(self) -> None:
        if self._closed:
            raise errors.ArgError("file is closed")

    # -- view (MPI_File_set_view / get_view) ------------------------------

    def set_view(self, disp: int, etype: Datatype,
                 filetype: Datatype | None = None,
                 rank: int | None = None) -> None:
        """Set the view for one rank, or every rank when rank is None (the
        common collective case where all ranks pass the same triple)."""
        self._check_open()
        view = _View(disp, etype, filetype or etype)
        with self._lock:
            if rank is None:
                self._views = [view] * len(self._views)
                self._pointers = [0] * len(self._pointers)
                self._shared = 0
            else:
                self._views[rank] = view
                self._pointers[rank] = 0

    def get_view(self, rank: int = 0) -> tuple[int, Datatype, Datatype]:
        v = self._views[rank]
        return v.disp, v.etype, v.filetype

    # -- byte-level engine ------------------------------------------------

    def _read_offsets(self, offsets: np.ndarray) -> np.ndarray:
        """Single-rank offset read, routed through fcoll -> fbtl (the
        OMPIO layering: strategy schedules, byte-transfer layer moves)."""
        return self._fcoll.read(self._fbtl, self._fd, [offsets])[0]

    def _write_offsets(self, offsets: np.ndarray, data: np.ndarray) -> None:
        self._fcoll.write(self._fbtl, self._fd, [(offsets, data)])

    def _as_bytes(self, buf, view: _View, count: int) -> np.ndarray:
        arr = np.ascontiguousarray(buf)
        data = arr.reshape(-1).view(np.uint8)
        need = count * view.etype.size
        if data.size < need:
            raise errors.TruncateError(
                f"buffer {data.size}B < {need}B ({count} etypes)"
            )
        return data[:need]

    # -- explicit-offset IO (MPI_File_read_at / write_at) -----------------

    def read_at(self, offset: int, count: int, rank: int = 0) -> np.ndarray:
        """Read `count` etypes at etype-offset `offset` through the rank's
        view; returns an array of the etype's numpy dtype (or raw bytes)."""
        self._check_open()
        v = self._views[rank]
        raw = self._read_offsets(v.byte_offsets(offset, count))
        dt = getattr(v.etype, "np_dtype", None)
        return raw.view(dt) if dt is not None else raw

    def _full_count(self, buf, v: _View) -> int:
        """Etype count of a whole buffer; rejects trailing partial etypes
        (same contract for every write entry point)."""
        nbytes = np.ascontiguousarray(buf).nbytes
        if v.etype.size and nbytes % v.etype.size:
            raise errors.TypeError_(
                f"buffer ({nbytes}B) is not a whole number of etypes "
                f"({v.etype.size}B)"
            )
        return nbytes // v.etype.size if v.etype.size else 0

    def write_at(self, offset: int, buf, count: int | None = None,
                 rank: int = 0) -> int:
        """Write `count` etypes (default: full buffer) at etype-offset
        `offset`; returns etypes written."""
        self._check_open()
        v = self._views[rank]
        if count is None:
            count = self._full_count(buf, v)
        data = self._as_bytes(buf, v, count)
        self._write_offsets(v.byte_offsets(offset, count), data)
        return count

    # -- nonblocking IO (MPI_File_iread/iwrite[_at]) ----------------------
    # Reference: ompi/mpi/c/file_iwrite.c:38 returning an ompio request
    # over the async fbtl (fbtl_posix_ipwritev.c).  The returned request
    # is the framework Request surface (wait/test); IO proceeds on the
    # fbtl worker while the caller computes.

    def _async_fbtl(self):
        from . import fbtl as fbtl_mod

        if not hasattr(self, "_ifbtl"):
            self._ifbtl = fbtl_mod.AsyncFbtl(self._fbtl)
        return self._ifbtl

    def iread_at(self, offset: int, count: int, rank: int = 0):
        """MPI_File_iread_at: request completing with the etype array."""
        self._check_open()
        v = self._views[rank]
        return iread_offsets(self._async_fbtl(), self._fcoll, self._fbtl,
                             self._fd, v.byte_offsets(offset, count),
                             getattr(v.etype, "np_dtype", None))

    def iwrite_at(self, offset: int, buf, count: int | None = None,
                  rank: int = 0):
        """MPI_File_iwrite_at: request completing with etypes written."""
        self._check_open()
        v = self._views[rank]
        if count is None:
            count = self._full_count(buf, v)
        return iwrite_offsets(self._async_fbtl(), self._fcoll, self._fbtl,
                              self._fd, v.byte_offsets(offset, count),
                              self._as_bytes(buf, v, count), count)

    def iread(self, count: int, rank: int = 0):
        """MPI_File_iread: nonblocking at the individual pointer (which
        advances immediately, per MPI's nonblocking-pointer contract)."""
        with self._lock:
            off = self._pointers[rank]
            self._pointers[rank] += count
        return self.iread_at(off, count, rank)

    def iwrite(self, buf, count: int | None = None, rank: int = 0):
        v = self._views[rank]
        if count is None:
            count = self._full_count(buf, v)
        with self._lock:
            off = self._pointers[rank]
            self._pointers[rank] += count
        return self.iwrite_at(off, buf, count, rank)

    # -- individual-pointer IO (MPI_File_read / write) --------------------

    def read(self, count: int, rank: int = 0) -> np.ndarray:
        with self._lock:
            off = self._pointers[rank]
            self._pointers[rank] += count
        return self.read_at(off, count, rank)

    def write(self, buf, count: int | None = None, rank: int = 0) -> int:
        v = self._views[rank]
        if count is None:
            count = self._full_count(buf, v)
        with self._lock:
            off = self._pointers[rank]
            self._pointers[rank] += count
        return self.write_at(off, buf, count, rank)

    def seek(self, offset: int, rank: int = 0) -> None:
        with self._lock:
            self._pointers[rank] = offset

    def tell(self, rank: int = 0) -> int:
        with self._lock:
            return self._pointers[rank]

    # -- shared-pointer IO (MPI_File_read/write_shared) -------------------

    def write_shared(self, buf, count: int | None = None) -> int:
        """Atomic fetch-and-add on the shared pointer then write through
        rank 0's view (sharedfp semantics: ordering is first-come)."""
        v = self._views[0]
        if count is None:
            count = self._full_count(buf, v)
        with self._lock:
            off = self._shared
            self._shared += count
        return self.write_at(off, buf, count, rank=0)

    def read_shared(self, count: int) -> np.ndarray:
        with self._lock:
            off = self._shared
            self._shared += count
        return self.read_at(off, count, rank=0)

    # -- collective IO (MPI_File_write_all / read_all) --------------------

    def write_all(self, bufs: list) -> int:
        """Every rank writes its buffer at its individual pointer through
        its view; extents from all ranks are sorted and coalesced into few
        large writes (the fcoll/two_phase aggregation, minus the exchange
        phase a single controller doesn't need).  Returns total etypes."""
        self._check_open()
        if len(bufs) != len(self._views):
            raise errors.ArgError(
                f"need one buffer per rank ({len(self._views)})"
            )
        per_rank, total = self._resolve_write_all(bufs, copy=False)
        # the selected fcoll strategy owns the aggregation shape
        self._fcoll.write(self._fbtl, self._fd, per_rank)
        return total

    def _resolve_write_all(self, bufs: list, copy: bool):
        """Shared write_all/iwrite_all body: per-rank counts, bytes and
        offsets resolved and pointers advanced under one lock (copy=True
        detaches the data for a worker that reads it later)."""
        per_rank, total = [], 0
        with self._lock:
            for r, buf in enumerate(bufs):
                v = self._views[r]
                count = self._full_count(buf, v)
                data = self._as_bytes(buf, v, count)
                offs = v.byte_offsets(self._pointers[r], count)
                self._pointers[r] += count
                per_rank.append((offs, data.copy() if copy else data))
                total += count
        return per_rank, total

    def _resolve_read_all(self, counts: list[int]):
        """Shared read_all/iread_all body: per-rank offsets + dtypes,
        pointers advanced under one lock."""
        offs_list, dts = [], []
        with self._lock:
            for r, count in enumerate(counts):
                v = self._views[r]
                offs_list.append(v.byte_offsets(self._pointers[r], count))
                self._pointers[r] += count
                dts.append(getattr(v.etype, "np_dtype", None))
        return offs_list, dts

    # -- nonblocking collective IO (MPI_File_iwrite_all/iread_all) -------
    # Single-controller forms: pointers advance at call time; the whole
    # aggregated pass retires on the async worker (the reference's
    # ompio iread_all over libnbc, collapsed to one submission because
    # no exchange phase exists on a single controller).

    def iwrite_all(self, bufs: list):
        self._check_open()
        if len(bufs) != len(self._views):
            raise errors.ArgError(
                f"need one buffer per rank ({len(self._views)})"
            )
        per_rank, total = self._resolve_write_all(bufs, copy=True)
        inner = self._async_fbtl().submit(
            self._fcoll.write, self._fbtl, self._fd, per_rank)
        return _MappedRequest(inner, lambda _: total)

    def iread_all(self, counts: list[int]):
        self._check_open()
        if len(counts) != len(self._views):
            raise errors.ArgError("need one count per rank")
        offs_list, dts = self._resolve_read_all(counts)
        inner = self._async_fbtl().submit(
            self._fcoll.read, self._fbtl, self._fd, offs_list)
        return _MappedRequest(inner, lambda raws: [
            raw.view(dt) if dt is not None else raw
            for raw, dt in zip(raws, dts)
        ])

    def read_all(self, counts: list[int]) -> list[np.ndarray]:
        """Collective read: rank r reads counts[r] etypes at its pointer.
        One aggregated pass over the file, then scatter to per-rank
        buffers."""
        self._check_open()
        if len(counts) != len(self._views):
            raise errors.ArgError("need one count per rank")
        per_rank_offs = []
        with self._lock:
            for r, count in enumerate(counts):
                v = self._views[r]
                per_rank_offs.append(v.byte_offsets(self._pointers[r], count))
                self._pointers[r] += count
        raws = self._fcoll.read(self._fbtl, self._fd, per_rank_offs)
        out = []
        for r, raw in enumerate(raws):
            dt = getattr(self._views[r].etype, "np_dtype", None)
            out.append(raw.view(dt) if dt is not None else raw)
        return out

    # -- size management --------------------------------------------------

    def get_size(self) -> int:
        self._check_open()
        return self._fs.size(self._fd)

    def set_size(self, size: int) -> None:
        self._check_open()
        self._fs.resize(self._fd, size)

    def preallocate(self, size: int) -> None:
        """MPI_File_preallocate: ensure `size` bytes exist."""
        self._check_open()
        if self._fs.size(self._fd) < size:
            self._fs.resize(self._fd, size)

    def sync(self) -> None:
        self._check_open()
        self._fs.sync(self._fd)


def delete(path: str) -> None:
    """MPI_File_delete."""
    fs_mod.select_fs().delete(path)
