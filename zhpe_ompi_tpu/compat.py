"""jax version-compatibility shims.

The tree targets current jax (``jax.shard_map``, the ``check_vma=``
spelling); the supported floor is the 0.4.x line, where the same
machine lives at ``jax.experimental.shard_map.shard_map`` with
``check_rep=``.  Without this shim the ENTIRE device plane — every
``Communicator.run``, ``make_train_step``, pgas epoch — dies at import
of the first SPMD program on an older container, which is exactly the
environment the CPU-loopback test rig runs in.  One shim keeps every
call site on the new spelling and translates down when needed.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True,
              **kwargs):
    """``jax.shard_map`` where available, else the 0.4.x experimental
    entry point with ``check_vma`` translated to its old ``check_rep``
    name (same semantics: replication/varying-manual-axes checking)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma,
                             **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma,
                      **kwargs)
