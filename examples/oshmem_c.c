/* oshmem_c — the C OpenSHMEM surface acceptance (zompi_shmem.h over
 * the window engine; reference oshmem/shmem/c):
 * symmetric allocation, ring put, every-PE fetch-add on one counter,
 * wait_until signaling, reductions, fcollect, and a lock-protected
 * critical section, across N real processes.
 *
 *   python -m zhpe_ompi_tpu.tools.zmpicc examples/oshmem_c.c -o oshmem
 *   python -m zhpe_ompi_tpu.tools.mpirun -n 4 ./oshmem
 */
#include <stdio.h>
#include <stdlib.h>
#include "zompi_shmem.h"

int main(void) {
  if (shmem_init() != 0) return 2;
  int me = shmem_my_pe(), n = shmem_n_pes();

  /* symmetric allocation: same offsets everywhere */
  long *ring = shmem_malloc(4 * sizeof(long));
  long *counter = shmem_malloc(sizeof(long));
  long *flag = shmem_malloc(sizeof(long));
  long *lock = shmem_malloc(sizeof(long));
  long *tally = shmem_malloc(sizeof(long));
  if (!ring || !counter || !flag || !lock || !tally) return 3;
  for (int i = 0; i < 4; i++) ring[i] = -1;
  *counter = 0; *flag = 0; *lock = 0; *tally = 0;
  shmem_barrier_all();

  /* ring put: my payload lands in my right neighbor's ring[] */
  long payload[4];
  for (int i = 0; i < 4; i++) payload[i] = me * 10 + i;
  shmem_long_put(ring, payload, 4, (me + 1) % n);
  shmem_barrier_all();
  int left = (me + n - 1) % n;
  for (int i = 0; i < 4; i++)
    if (ring[i] != left * 10 + i) {
      fprintf(stderr, "PE %d: ring[%d]=%ld\n", me, i, ring[i]);
      return 4;
    }

  /* the canonical idiom: every PE fetch-adds PE 0's counter; fetches
   * must be distinct linearization points and the total exact */
  long old = shmem_long_atomic_fetch_add(counter, me + 1, 0);
  if (old < 0 || old > (long)n * (n + 1) / 2) return 5;
  shmem_barrier_all();
  if (me == 0 && *counter != (long)n * (n + 1) / 2) {
    fprintf(stderr, "counter %ld\n", *counter);
    return 6;
  }

  /* wait_until: PE 0 signals PE n-1 */
  if (me == 0) shmem_long_p(flag, 42, n - 1);
  if (me == n - 1) {
    shmem_long_wait_until(flag, SHMEM_CMP_EQ, 42);
  }
  shmem_barrier_all();

  /* reductions + fcollect */
  long lv = me + 1, lsum = 0, lmax = 0;
  shmem_long_sum_reduce(&lsum, &lv, 1);
  shmem_long_max_reduce(&lmax, &lv, 1);
  if (lsum != (long)n * (n + 1) / 2 || lmax != n) return 7;
  long *gathered = shmem_malloc(n * sizeof(long));
  shmem_fcollectmem(gathered, &lv, sizeof(long));
  for (int i = 0; i < n; i++)
    if (gathered[i] != i + 1) return 8;

  /* lock-protected read-modify-write (NOT atomic ops: the lock is the
   * serialization) — every PE increments the tally 3 times */
  for (int k = 0; k < 3; k++) {
    shmem_set_lock(lock);
    long t = shmem_long_g(tally, 0);
    shmem_long_p(tally, t + 1, 0);
    shmem_quiet();
    shmem_clear_lock(lock);
  }
  shmem_barrier_all();
  if (me == 0 && *tally != 3L * n) {
    fprintf(stderr, "tally %ld != %ld\n", *tally, 3L * n);
    return 9;
  }

  /* broadcast */
  double src = me == 1 ? 2.718 : 0.0, dst = -1.0;
  shmem_broadcastmem(&dst, &src, sizeof dst, 1);
  if (dst != 2.718) return 10;

  /* implicit-handle nonblocking RMA: nb put to the right neighbor and
   * nb gets from every PE, all completing at one quiet */
  long *nbv = shmem_malloc(sizeof(long));
  *nbv = me * 7;
  shmem_barrier_all();
  long mark = me * 7 + 1000;
  shmem_putmem_nbi(nbv, &mark, sizeof mark, (me + 1) % n);
  shmem_quiet();
  shmem_barrier_all();
  if (*nbv != ((me + n - 1) % n) * 7 + 1000) return 11;
  long *fetched = malloc(n * sizeof(long));
  for (int p = 0; p < n; p++) {
    fetched[p] = -1;
    shmem_getmem_nbi(&fetched[p], nbv, sizeof(long), p);
  }
  shmem_quiet();
  for (int p = 0; p < n; p++)
    if (fetched[p] != ((p + n - 1) % n) * 7 + 1000) return 12;
  free(fetched);
  shmem_free(nbv);

  shmem_free(gathered);
  shmem_free(ring);

  /* ---- round-5 completion tier ---- */
  /* align: symmetric OFFSET alignment (and absolute, page-aligned
   * heap), usable as a put target */
  long *av = shmem_align(256, sizeof(long));
  if (!av || ((unsigned long)av & 255)) return 13;
  *av = -5;
  shmem_barrier_all();
  long stamp = 4000 + me;
  shmem_putmem(av, &stamp, sizeof stamp, (me + 1) % n);
  shmem_barrier_all();
  if (*av != 4000 + (me + n - 1) % n) return 14;
  /* realloc preserves contents and stays symmetric */
  av = shmem_realloc(av, 4 * sizeof(long));
  if (!av || *av != 4000 + (me + n - 1) % n) return 15;
  shmem_free(av);

  /* accessibility + ptr */
  if (!shmem_pe_accessible(0) || shmem_pe_accessible(n + 5)) return 16;
  long *probe = shmem_malloc(sizeof(long));
  if (!shmem_addr_accessible(probe, (me + 1) % n)) return 17;
  if (shmem_ptr(probe, me) != probe) return 18;
  if (n > 1 && shmem_ptr(probe, (me + 1) % n) != NULL) return 19;

  /* strided iput into the right neighbor */
  long *grid = shmem_malloc(8 * sizeof(long));
  for (int i = 0; i < 8; i++) grid[i] = -1;
  long stv[2] = {me * 100, me * 100 + 1};
  shmem_barrier_all();
  shmem_long_iput(grid, stv, 3, 1, 2, (me + 1) % n); /* slots 0,3 */
  shmem_barrier_all();
  int lpe = (me + n - 1) % n;
  if (grid[0] != lpe * 100 || grid[3] != lpe * 100 + 1) return 20;
  if (grid[1] != -1 || grid[2] != -1) return 21;
  long back[2] = {-9, -9};
  shmem_long_iget(back, grid, 1, 3, 2, me); /* read 0,3 back */
  if (back[0] != lpe * 100 || back[1] != lpe * 100 + 1) return 22;
  shmem_free(grid);

  /* alltoall + collect */
  long *a2src = shmem_malloc(n * sizeof(long));
  long *a2dst = shmem_malloc(n * sizeof(long));
  for (int p = 0; p < n; p++) a2src[p] = me * 1000 + p;
  shmem_barrier_all();
  shmem_alltoallmem(a2dst, a2src, sizeof(long));
  for (int p = 0; p < n; p++)
    if (a2dst[p] != p * 1000 + me) return 23;
  shmem_free(a2src);
  shmem_free(a2dst);
  shmem_sync_all();

  int maj = -1, min = -1;
  shmem_info_get_version(&maj, &min);
  if (maj != 1 || min != 4) return 24;
  char libname[SHMEM_MAX_NAME_LEN];
  shmem_info_get_name(libname);
  if (!libname[0]) return 25;
  shmem_udcflush(); /* deprecated cache ops: link + no-op */
  if (_my_pe() != me || _num_pes() != n) return 26;
  shmem_free(probe);

  shmem_barrier_all();
  printf("oshmem_c PE %d/%d OK\n", me, n);
  shmem_finalize();
  return 0;
}
