"""Hierarchical data parallelism (parallel/hybrid.py): host-plane (DCN)
gradient sync across launcher processes composes with in-process compute
to the exact full-batch gradient, and parameter bcast repairs slice
divergence.  The ICI-inside/DCN-outside shape of multi-slice scaling."""

import io
import os
import textwrap

import numpy as np

from zhpe_ompi_tpu.tools import mpirun

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pack_unpack_roundtrip_mixed_dtypes():
    import jax

    from zhpe_ompi_tpu.parallel import hybrid

    tree = {
        "a": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": {"c": np.ones(4, np.float64), "d": np.zeros((), np.float32)},
    }
    bufs, treedef, meta = hybrid.pack_tree(tree)
    assert set(bufs) == {"float32", "float64"}
    out = hybrid.unpack_tree(bufs, treedef, meta)
    flat_in = jax.tree_util.tree_leaves(tree)
    flat_out = jax.tree_util.tree_leaves(out)
    for a, b in zip(flat_in, flat_out):
        np.testing.assert_array_equal(np.asarray(a), b)
        assert np.asarray(a).shape == b.shape


def test_two_slice_grad_sync_matches_full_batch(tmp_path):
    """2 launcher processes each grad a half batch; dcn_grad_sync must
    reproduce the single-process full-batch gradient exactly."""
    prog = tmp_path / "slice.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {_REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.models import transformer as tfm
        from zhpe_ompi_tpu.parallel import hybrid

        proc = zmpi.host_init()
        cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                         n_layers=2, seq=8, dtype=jnp.float32)
        params = tfm.init_params(cfg, jax.random.PRNGKey(0))
        r = np.random.default_rng(0)
        tok = r.integers(0, cfg.vocab, (8, cfg.seq))
        tgt = r.integers(0, cfg.vocab, (8, cfg.seq))
        lo, hi = proc.rank * 4, proc.rank * 4 + 4
        loss = lambda p: tfm.loss_fn(
            p, jnp.asarray(tok[lo:hi]), jnp.asarray(tgt[lo:hi]), cfg)
        grads = jax.grad(loss)(params)
        synced = hybrid.dcn_grad_sync(proc, grads)
        if proc.rank == 0:
            np.savez(os.path.join({str(tmp_path)!r}, "synced.npz"),
                     **{{k: np.asarray(v) for k, v in synced.items()}})
            print("SYNC-DONE")
        proc.barrier()
        zmpi.host_finalize()
    """))
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(2, [str(prog)], stdout=out, stderr=err,
                       timeout=180.0)
    assert rc == 0, err.getvalue()
    assert "SYNC-DONE" in out.getvalue()

    # single-process full-batch reference
    import jax
    import jax.numpy as jnp

    from zhpe_ompi_tpu.models import transformer as tfm

    cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                     n_layers=2, seq=8, dtype=jnp.float32)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    r = np.random.default_rng(0)
    tok = r.integers(0, cfg.vocab, (8, cfg.seq))
    tgt = r.integers(0, cfg.vocab, (8, cfg.seq))
    ref = jax.grad(lambda p: tfm.loss_fn(
        p, jnp.asarray(tok), jnp.asarray(tgt), cfg))(params)

    got = np.load(os.path.join(str(tmp_path), "synced.npz"))
    for k, v in ref.items():
        np.testing.assert_allclose(
            got[k], np.asarray(v), rtol=2e-5, atol=2e-6,
        )


def test_param_bcast_repairs_divergence(tmp_path):
    prog = tmp_path / "bc.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {_REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import numpy as np
        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.parallel import hybrid

        proc = zmpi.host_init()
        params = {{"w": np.full((64,), float(proc.rank), np.float32),
                   "b": np.arange(8, dtype=np.float64) * (proc.rank + 1)}}
        fixed = hybrid.dcn_bcast_params(proc, params, root=1)
        w = np.asarray(fixed["w"]) if not isinstance(fixed["w"], np.ndarray) else fixed["w"]
        assert (w == 1.0).all(), w[:4]
        assert np.allclose(np.asarray(fixed["b"]),
                           np.arange(8, dtype=np.float64) * 2)
        proc.barrier()
        if proc.rank == 0:
            print("BCAST-OK")
        zmpi.host_finalize()
    """))
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(3, [str(prog)], stdout=out, stderr=err,
                       timeout=120.0)
    assert rc == 0, err.getvalue()
    assert "BCAST-OK" in out.getvalue()


def test_bfloat16_grads_sync_and_bcast(tmp_path):
    """bfloat16 — the TPU training dtype — must survive the DCN sync
    (transport as lossless f32 upcast) and bit-exact param bcast."""
    prog = tmp_path / "bf16.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {_REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import numpy as np
        import ml_dtypes
        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.parallel import hybrid

        proc = zmpi.host_init()
        bf = ml_dtypes.bfloat16
        grads = {{"w": np.full(16, proc.rank + 1, bf),
                  "b": np.ones(4, np.float32) * proc.rank}}
        synced = hybrid.dcn_grad_sync(proc, grads)
        assert synced["w"].dtype == np.dtype("bfloat16"), synced["w"].dtype
        assert np.allclose(synced["w"].astype(np.float32), 1.5)  # mean 1,2
        assert np.allclose(synced["b"], 0.5)
        fixed = hybrid.dcn_bcast_params(
            proc, {{"w": (np.arange(8, dtype=np.float32)
                          * (proc.rank + 1)).astype(bf)}}, root=0)
        assert fixed["w"].dtype == np.dtype("bfloat16")
        assert (fixed["w"].astype(np.float32)
                == np.arange(8, dtype=np.float32)).all()
        proc.barrier()
        if proc.rank == 0:
            print("BF16-OK")
        zmpi.host_finalize()
    """))
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(2, [str(prog)], stdout=out, stderr=err,
                       timeout=120.0)
    assert rc == 0, err.getvalue()
    assert "BF16-OK" in out.getvalue()


def test_single_slice_returns_numpy_leaves():
    import types

    from zhpe_ompi_tpu.parallel import hybrid

    proc = types.SimpleNamespace(size=1, rank=0)
    import jax.numpy as jnp

    got = hybrid.dcn_grad_sync(proc, {"w": jnp.ones(3, jnp.float32)})
    assert isinstance(got["w"], np.ndarray)


def test_multislice_adam_matches_full_batch(tmp_path):
    """The full composition: 2 launcher slices each run the optax train
    step with dcn_proc set; after 2 steps their params must match a
    single-process full-batch Adam run."""
    prog = tmp_path / "adam_slice.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {_REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.models import transformer as tfm

        proc = zmpi.host_init()
        cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                         n_layers=2, seq=8, dtype=jnp.float32)
        mesh = Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                    ("dp", "tp"))
        dpc = zmpi.Communicator(mesh, "dp")
        init_state, step, specs = tfm.make_train_step_optax(
            cfg, mesh, dpc, None, optimizer=optax.adam(1e-2),
            dcn_proc=proc)
        params = {{k: jax.device_put(np.asarray(v),
                                     NamedSharding(mesh, specs[k]))
                   for k, v in tfm.init_params(
                       cfg, jax.random.PRNGKey(0)).items()}}
        st = init_state(params)
        r = np.random.default_rng(0)
        tok = r.integers(0, cfg.vocab, (8, cfg.seq))
        tgt = r.integers(0, cfg.vocab, (8, cfg.seq))
        lo = proc.rank * 4
        ds = NamedSharding(mesh, P("dp"))
        mtok = jax.device_put(jnp.asarray(tok[lo:lo+4]), ds)
        mtgt = jax.device_put(jnp.asarray(tgt[lo:lo+4]), ds)
        for _ in range(2):
            params, st, loss = step(params, st, mtok, mtgt)
        if proc.rank == 0:
            np.savez(os.path.join({str(tmp_path)!r}, "slice_params.npz"),
                     **{{k: np.asarray(v) for k, v in params.items()}})
            print("ADAM-SLICES-DONE")
        proc.barrier()
        zmpi.host_finalize()
    """))
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(2, [str(prog)], stdout=out, stderr=err,
                       timeout=240.0)
    assert rc == 0, err.getvalue()
    assert "ADAM-SLICES-DONE" in out.getvalue()

    # single-process full-batch reference
    import jax
    import jax.numpy as jnp
    import optax

    from zhpe_ompi_tpu.models import transformer as tfm

    cfg = tfm.Config(vocab=64, d_model=16, n_heads=2, d_ff=32,
                     n_layers=2, seq=8, dtype=jnp.float32)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    opt = optax.adam(1e-2)
    st = opt.init(params)
    r = np.random.default_rng(0)
    tok = jnp.asarray(r.integers(0, cfg.vocab, (8, cfg.seq)))
    tgt = jnp.asarray(r.integers(0, cfg.vocab, (8, cfg.seq)))
    for _ in range(2):
        grads = jax.grad(lambda p: tfm.loss_fn(p, tok, tgt, cfg))(params)
        upd, st = opt.update(grads, st, params)
        params = optax.apply_updates(params, upd)

    got = np.load(os.path.join(str(tmp_path), "slice_params.npz"))
    for k, v in params.items():
        np.testing.assert_allclose(got[k], np.asarray(v),
                                   rtol=5e-5, atol=5e-6, err_msg=k)


def test_two_slice_sharded_sync_matches_full_gather(tmp_path):
    """Per-shard DCN sync (round 4, the memory-cliff scaling path):
    2 slices x 4 virtual devices with tp-sharded gradients — the
    shard-wise reduction must reproduce dcn_grad_sync's full-gather
    result exactly, with every output shard on its original device."""
    prog = tmp_path / "shardsync.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {_REPO!r})
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        import zhpe_ompi_tpu as zmpi
        from zhpe_ompi_tpu.parallel import hybrid

        proc = zmpi.host_init()
        mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ("tp",))
        r = np.random.default_rng(proc.rank)
        tree = {{
            "w_sharded": jax.device_put(
                jnp.asarray(r.normal(size=(8, 6)), jnp.float32),
                NamedSharding(mesh, P("tp", None))),
            "w_repl": jax.device_put(
                jnp.asarray(r.normal(size=(5,)), jnp.float32),
                NamedSharding(mesh, P())),
            "w_bf16": jax.device_put(
                jnp.asarray(r.normal(size=(4, 4)), jnp.bfloat16),
                NamedSharding(mesh, P("tp"))),
            "scalar": np.float32(proc.rank + 1.0),
        }}
        synced = hybrid.dcn_grad_sync_sharded(proc, tree)
        full = hybrid.dcn_grad_sync(proc, tree)
        # shard-wise result == full-gather result, and shardings kept
        for k in tree:
            a = np.asarray(synced[k], np.float32)
            b = np.asarray(full[k], np.float32)
            assert np.allclose(a, b, rtol=1e-6), (k, a, b)
        assert synced["w_sharded"].sharding.is_equivalent_to(
            tree["w_sharded"].sharding, 2)
        assert synced["w_bf16"].dtype == jnp.bfloat16
        if proc.rank == 0:
            print("SHARD-SYNC-OK")
        proc.barrier()
        zmpi.host_finalize()
    """))
    out, err = io.StringIO(), io.StringIO()
    rc = mpirun.launch(2, [str(prog)], stdout=out, stderr=err,
                       timeout=180.0)
    assert rc == 0, err.getvalue()
    assert "SHARD-SYNC-OK" in out.getvalue()


def test_sharded_sync_dedups_replicas_and_checks_layout():
    """In-process unit checks on the per-shard sync: a dp-replicated,
    tp-sharded leaf reduces each DISTINCT shard once (not once per
    replica), and mismatched layouts across slices raise before any
    data moves."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from zhpe_ompi_tpu.core import errors
    from zhpe_ompi_tpu.parallel import hybrid

    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("dp", "tp"))

    class FakeProc:
        """Two identical 'slices' collapsed into one process: allreduce
        doubles (sum of two equal contributions), allgather echoes."""

        size = 2

        def __init__(self):
            self.reduce_calls = 0
            self.peer_digest = None

        def allreduce(self, x, op):
            self.reduce_calls += 1
            return x * 2

        def allgather(self, x):
            return [x, self.peer_digest if self.peer_digest else x]

    proc = FakeProc()
    leaf = jax.device_put(
        jnp.arange(8, dtype=jnp.float32).reshape(2, 4),
        NamedSharding(mesh, P(None, "tp")),  # tp-sharded, dp-replicated
    )
    synced = hybrid.dcn_grad_sync_sharded(proc, {"w": leaf})
    # 4 devices hold 2 DISTINCT tp shards -> exactly 2 reduces
    assert proc.reduce_calls == 2, proc.reduce_calls
    # w = 1/size = 0.5, allreduce doubles: mean of two equal slices = x
    np.testing.assert_allclose(np.asarray(synced["w"]),
                               np.arange(8, dtype=np.float32).reshape(2, 4))
    assert synced["w"].sharding.is_equivalent_to(leaf.sharding, 2)

    # layout mismatch: peer reports a different fingerprint -> raise
    import pytest

    proc2 = FakeProc()
    proc2.peer_digest = "not-the-same"
    with pytest.raises(errors.ArgError, match="fingerprints differ"):
        hybrid.dcn_grad_sync_sharded(proc2, {"w": leaf})
