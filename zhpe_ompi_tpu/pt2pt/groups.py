"""Locality groups — the topology layer under the hierarchical (han)
host collectives.

The reference's ``coll/han`` splits every collective into an intra-node
phase and an inter-node phase among one leader per node (Luo et al.,
"HAN: a Hierarchical AutotuNed Collective Communication Framework",
IEEE Cluster 2020).  Its topology input is the proc locality the RTE
publishes; ours is the ``(boot_id, segment)`` card the shared-memory
transport (``pt2pt/sm.py``) already advertises on the modex — two ranks
with equal boot tokens are provably one ``/dev/shm`` namespace, i.e.
one host.  This module derives those **locality groups** and exposes a
:class:`GroupView`: a lightweight sub-endpoint over any endpoint's
``rank``/``size``/``send``/``recv``/``sendrecv`` surface with

- **relative ranks** — members renumbered densely 0..m-1, so the flat
  algorithms in ``coll/host.py`` run on a subgroup unchanged (the same
  layering trick as :class:`~zhpe_ompi_tpu.ft.ulfm.ShrunkEndpoint`);
- **a disjoint tag window** — every view translates its traffic onto a
  per-window cid (``_HAN_CID_BASE + window``) with a per-window
  collective sequence kept ON the parent endpoint, so concurrent
  subgroup collectives (each host's intra phase runs at the same time)
  and interleaved parent-level flat collectives can never cross-match;
- **phase accounting** — every send records its payload bytes into
  ``coll_han_intra_bytes`` or ``coll_han_inter_bytes``, the counters
  the OSU han ladder gates on.

Because a view only *translates*, the transport fast paths arrive for
free through the send seam: an intra-phase send between same-boot ranks
rides the mmap rings, a leader-phase send rides the zero-copy wire —
exactly the property that makes two-level algorithms win (a flat ring
that interleaves sm and wire hops runs at the speed of its slowest
hop).

FT coexistence: views resolve the parent chain's ``FailureState`` and
register their window cid as an **alias** of the logical collective cid
(``coll/host.py``'s COLL_CID), so ``revoke(COLL_CID)`` poisons parked
and future subgroup operations with the same typed ``Revoked`` the flat
path raises, and peer death classifies through the parent's receive
path untouched.  A shrink produces a fresh endpoint, so its first han
collective derives fresh groups (the rebuild contract).

Hygiene: window registrations are tracked per endpoint; a closed
endpoint (``TcpProc.close`` calls :func:`release`) must hold none — the
conftest session gate asserts :func:`leaked_tag_windows` is empty, and
:func:`live_election_threads` guards that leader election stays the
deterministic min-rank rule (no thread may ever outlive it).
"""

from __future__ import annotations

import threading
import weakref
from typing import Any

from ..coll.host import COLL_CID
from ..core import errors
from ..runtime import spc
from ..utils.payload import payload_size_estimate as payload_bytes
from . import sm as sm_mod

# One cid per tag window: groups 0..253 plus the leader window.  The
# whole span sits below every control/collective cid in use (user cids
# are small, COLL_CID/barrier live at 0x7FF0+) and within 16 bits, so a
# view over a ShrunkEndpoint survives the generation translation
# (_shrink_cid masks cid & 0xFFFF).
_HAN_CID_BASE = 0x7900
_HAN_WINDOWS = 0x100
LEADER_WINDOW = _HAN_WINDOWS - 1  # the inter-phase (leader) window
MAX_GROUPS = LEADER_WINDOW       # group i owns window i (two-level)

# three-level (NUMA) window partition.  A window id's tag sequence is
# uniform only among ONE member set, so every window range must be
# DISJOINT from every other range that can coexist on an endpoint:
# host windows (two-level intra; also the three-level nesting parent)
# keep 0..0x3F, intra-DOMAIN views own DOMAIN_WINDOW_BASE + global
# domain index, each host's domain-leader exchange owns
# HOST_LEADER_BASE + host index, and the inter-host leader window
# stays LEADER_WINDOW.  A topology too large for the partition is not
# NUMA-viable and runs two-level (which alone may still use the full
# 0..MAX_GROUPS-1 span — no domain windows exist to collide with).
DOMAIN_WINDOW_BASE = 0x40
DOMAIN_WINDOWS = 0x40                        # <= 64 domains total
HOST_LEADER_BASE = 0x80
MAX_HOSTS_NESTED = DOMAIN_WINDOW_BASE        # <= 64 hosts when nested

#: plane -> SPC byte counter of a GroupView's send seam ("dleader" is
#: the three-level intra-host leader exchange — same-host sm traffic,
#: accounted apart from both the domain phase and the wire phase)
PLANE_COUNTERS = {
    "intra": "coll_han_intra_bytes",
    "inter": "coll_han_inter_bytes",
    "dleader": "coll_han_dleader_bytes",
}

# endpoint -> set of registered window ids (weak: a collected endpoint
# takes its registrations with it); the leak gate inspects what is left
_reg_lock = threading.Lock()
_registrations: "weakref.WeakKeyDictionary[Any, set[int]]" = \
    weakref.WeakKeyDictionary()

# leader election is the deterministic min-rank rule — no threads, by
# design.  The registry exists so the hygiene gate keeps asserting that
# if an asynchronous election ever lands, its threads cannot leak.
_election_threads: list[threading.Thread] = []


def boot_token_of(ep, rank: int) -> str | None:
    """Locality identity of ``rank`` on ``ep``: endpoints expose
    ``boot_token_of`` (TcpProc reads the modex cards, thread ranks are
    one process, shrunk endpoints translate to their parent); None =
    unknown, grouped as its own singleton locality."""
    fn = getattr(ep, "boot_token_of", None)
    if fn is None:
        return None
    return fn(rank)


def numa_token_of(ep, rank: int):
    """NUMA-domain identity of ``rank`` on ``ep``: the token string,
    ``None`` when unknown (old cards, sm=0 peers — the host degrades
    to one domain), or :data:`~zhpe_ompi_tpu.pt2pt.sm.NUMA_MALFORMED`.
    Exception-safe by contract: a malformed FOREIGN card must never
    raise out of a collective's topology derivation — it is counted
    and demoted to the sentinel instead."""
    fn = getattr(ep, "numa_token_of", None)
    if fn is None:
        return None
    try:
        return fn(rank)
    # zlint: disable=ZL004 -- classified degradation: the MALFORMED sentinel is counted (han_malformed_numa_cards) and demoted to a singleton domain by the topology layer (PR 9)
    except Exception:  # noqa: BLE001 - foreign-card robustness
        return sm_mod.NUMA_MALFORMED


def locality_groups(ep, nested: bool = False):
    """Same-host groups of ``ep``'s ranks, derived from the modex boot
    tokens: a list of ascending-rank member lists, ordered by leader
    (minimum) rank.  Ranks with no provable locality (no card, sm=0
    peers, C ranks, rejoiners) are their own singleton group — han then
    treats them as one-rank hosts, which is always correct and merely
    forgoes an intra phase for them.

    With ``nested=True`` the structure gains the NUMA level: each host
    entry becomes a list of DOMAIN member-lists (ordered by domain
    leader), derived from the ``pynuma:`` card tokens.  The derivation
    ladder per rank: token present → its domain; token absent (old
    card) → the host's single default domain; token malformed →
    counted (``han_malformed_numa_cards``) and demoted to a singleton
    domain.  It never raises — a host whose members advertise no
    usable tokens is simply one domain, i.e. exactly the two-level
    structure."""
    size = getattr(ep, "size", 1)
    by_token: dict[str, list[int]] = {}
    groups: list[list[int]] = []
    for r in range(size):
        tok = boot_token_of(ep, r)
        if tok is None:
            groups.append([r])
            continue
        members = by_token.get(tok)
        if members is None:
            members = by_token[tok] = [r]
            groups.append(members)
        else:
            members.append(r)
    groups.sort(key=lambda g: g[0])
    if not nested:
        return groups
    out: list[list[list[int]]] = []
    for g in groups:
        if len(g) == 1:
            out.append([list(g)])
            continue
        by_dom: dict[str, list[int]] = {}
        domains: list[list[int]] = []
        for r in g:
            tok = numa_token_of(ep, r)
            if tok is sm_mod.NUMA_MALFORMED:
                spc.record("han_malformed_numa_cards", 1)
                domains.append([r])  # singleton domain, never a raise
                continue
            if tok is None:
                tok = ""  # absent: the host's shared default domain
            members = by_dom.get(tok)
            if members is None:
                members = by_dom[tok] = [r]
                domains.append(members)
            else:
                members.append(r)
        domains.sort(key=lambda d: d[0])
        out.append(domains)
    return out


def _ft_state(ep):
    """Nearest FailureState up the endpoint chain (ShrunkEndpoint and
    views wrap their parent; the state lives on the transport)."""
    seen = 0
    while ep is not None and seen < 8:
        state = getattr(ep, "ft_state", None)
        if state is not None:
            return state
        ep = getattr(ep, "_ep", None)
        seen += 1
    return None


def _window_seqs(ep) -> dict[int, int]:
    """Per-window collective sequence counters, kept on the ENDPOINT so
    re-created views over the same window continue the tag sequence
    (the reason two successive han collectives can never cross-match
    even though each built its views afresh)."""
    seqs = getattr(ep, "_han_window_seqs", None)
    if seqs is None:
        seqs = {}
        ep._han_window_seqs = seqs
    return seqs


def _transport_of(ep):
    """The close-owning endpoint under any wrapper chain (fault
    injection proxies, shrunk endpoints, nested views all expose the
    parent as ``_ep``): window registrations must key on the object
    whose ``close()`` releases them, or the hygiene gate would flag
    wrappers nobody closes."""
    seen = 0
    while seen < 8:
        inner = getattr(ep, "_ep", None)
        if inner is None:
            return ep
        ep = inner
        seen += 1
    return ep


def _register(ep, window: int) -> None:
    owner = _transport_of(ep)
    with _reg_lock:
        wids = _registrations.get(owner)
        if wids is None:
            wids = set()
            _registrations[owner] = wids
        wids.add(window)


def release(ep) -> None:
    """Drop every tag-window registration of ``ep`` (called from the
    endpoint's close(); thread-plane endpoints release by GC)."""
    with _reg_lock:
        _registrations.pop(ep, None)


def leaked_tag_windows() -> list[str]:
    """Window registrations whose endpoint is already CLOSED — the
    hygiene gate's view (an open endpoint legitimately keeps its
    windows for its next collective)."""
    with _reg_lock:
        items = list(_registrations.items())
    out = []
    for ep, wids in items:
        closed = getattr(ep, "_closed", None)
        if closed is not None and closed.is_set():
            out.append(f"{type(ep).__name__}(rank={getattr(ep, 'rank', '?')})"
                       f":windows={sorted(wids)}")
    return sorted(out)


def live_election_threads() -> list[str]:
    """Leader-election threads still alive — [] by construction (the
    min-rank rule is synchronous); asserted by the session gate."""
    _election_threads[:] = [t for t in _election_threads if t.is_alive()]
    return [t.name for t in _election_threads]


class GroupView:
    """Sub-endpoint over one locality group (or a leader set): the
    flat host-plane algorithms run on it unchanged while the traffic
    stays inside a disjoint tag window of the parent endpoint.

    ``plane`` is ``"intra"``, ``"dleader"`` or ``"inter"`` — it selects
    the SPC byte counter and documents which han phase the view
    carries.

    A view may be built OVER ANOTHER VIEW (the three-level NUMA
    schedule nests its domain views inside the host view): ``members``
    are then ranks of that parent view, and the nested view flattens
    the chain — its traffic translates straight onto the BASE endpoint
    with the nested view's OWN window cid (never the parent's), its
    per-window sequence lives on the base endpoint (recreated nested
    views continue the sequence), and its window registration keys on
    the close-owning transport.  ``rel``/``parent_rank`` stay
    parent-relative; ``base_rank``/``rel_base`` translate to the base
    endpoint."""

    # coll/host.py's han seam checks this to re-enter the FLAT
    # algorithms for phase traffic (no recursive hierarchy)
    _han_subview = True

    def __init__(self, ep, members: list[int], window: int,
                 plane: str = "intra"):
        if ep.rank not in members:
            raise errors.ArgError(
                f"rank {ep.rank} building a view it is not a member of "
                f"({members})"
            )
        self._parent = ep
        self._pmembers = list(members)      # view rank -> parent rank
        self._pinv = {g: i for i, g in enumerate(self._pmembers)}
        if isinstance(ep, GroupView):
            # view-of-view: collapse to the base endpoint so nested
            # phases pay ONE translation, not a tower — and so the
            # window cid on the wire is this view's, not the parent's
            base = ep._ep
            base_members = [ep._members[m] for m in members]
        else:
            base = ep
            base_members = list(members)
        self._ep = base
        self._members = base_members        # view rank -> base rank
        self._inv = {g: i for i, g in enumerate(self._members)}
        self.rank = self._inv[base.rank]
        self.size = len(self._members)
        self._window = int(window) % _HAN_WINDOWS
        self._cid = _HAN_CID_BASE + self._window
        self._plane = plane
        self._bytes_counter = PLANE_COUNTERS.get(
            plane, "coll_han_intra_bytes")
        self._seqs = _window_seqs(base)
        state = _ft_state(base)
        if state is not None and hasattr(state, "alias_cid"):
            # revoke(COLL_CID) must poison the window's parked and
            # future operations exactly like the flat path's
            state.alias_cid(self._cid, COLL_CID)
        _register(base, self._window)

    # -- per-window collective sequence (read/written by coll/host's
    # _next_tag through the ordinary attribute protocol) ----------------

    @property
    def _coll_seq(self) -> int:
        return self._seqs.get(self._window, 0)

    @_coll_seq.setter
    def _coll_seq(self, value: int) -> None:
        self._seqs[self._window] = value

    # -- nonblocking-schedule support (coll/nbc over a view) -------------

    @property
    def ft_state(self):
        """Nearest FailureState up the parent chain (None on non-ft
        endpoints): an nbc schedule running on a view stays
        revoke-aware — its window cid is aliased to COLL_CID, so the
        schedule's revocation checks resolve through the same alias
        machinery as the blocking phases'."""
        return _ft_state(self._ep)

    def progress(self) -> None:
        """Drive the parent's progress engine (thread-plane mailbox
        delivery); socket endpoints progress from their drain threads
        and this is a no-op."""
        fn = getattr(self._ep, "progress", None)
        if fn is not None:
            fn()

    # -- translation helpers ---------------------------------------------

    def rel(self, parent_rank: int) -> int:
        """View rank of a PARENT rank (ArgError for non-members) — the
        parent is whatever the view was built over, another view
        included."""
        try:
            return self._pinv[parent_rank]
        except KeyError:
            raise errors.ArgError(
                f"parent rank {parent_rank} is not a member of this view"
            ) from None

    def parent_rank(self, view_rank: int) -> int:
        return self._pmembers[view_rank]

    def base_rank(self, view_rank: int) -> int:
        """Rank of a view member on the BASE endpoint (== parent_rank
        unless this view was built over another view)."""
        return self._members[view_rank]

    def rel_base(self, base_rank: int) -> int:
        """View rank of a base-endpoint rank (ArgError for
        non-members) — the inverse of :meth:`base_rank`."""
        try:
            return self._inv[base_rank]
        except KeyError:
            raise errors.ArgError(
                f"base rank {base_rank} is not a member of this view"
            ) from None

    def boot_token_of(self, rank: int) -> str | None:
        return boot_token_of(self._ep, self._members[rank])

    def _xsrc(self, source: int) -> int:
        return source if source == -1 else self._members[source]

    # -- endpoint surface (the coll/host contract) -----------------------

    def send(self, obj: Any, dest: int, tag: int = 0, cid: int = 0) -> None:
        spc.record(self._bytes_counter, payload_bytes(obj))
        self._ep.send(obj, self._members[dest], tag, self._cid)

    def isend(self, obj: Any, dest: int, tag: int = 0, cid: int = 0):
        spc.record(self._bytes_counter, payload_bytes(obj))
        return self._ep.isend(obj, self._members[dest], tag, self._cid)

    def recv(self, source: int = -1, tag: int = -1, cid: int = 0,
             timeout: float | None = None, return_status: bool = False):
        out = self._ep.recv(self._xsrc(source), tag, self._cid,
                            timeout=timeout, return_status=return_status)
        if return_status:
            value, status = out
            if status.source >= 0:
                status.source = self._inv.get(status.source, -1)
            return value, status
        return out

    def irecv(self, source: int = -1, tag: int = -1, cid: int = 0):
        return self._ep.irecv(self._xsrc(source), tag, self._cid)

    def sendrecv(self, obj: Any, dest: int, source: int = -1,
                 sendtag: int = 0, recvtag: int = -1, cid: int = 0):
        spc.record(self._bytes_counter, payload_bytes(obj))
        return self._ep.sendrecv(obj, self._members[dest],
                                 source=self._xsrc(source),
                                 sendtag=sendtag, recvtag=recvtag,
                                 cid=self._cid)

    def __repr__(self):  # pragma: no cover
        return (f"GroupView({self._plane}, rank={self.rank}/{self.size}, "
                f"parents={self._members}, window={self._window:#x})")
