"""Serving plane — fault-tolerant continuous-batching inference loop.

:class:`~zhpe_ompi_tpu.models.ftloop.FtTrainLoop`'s inference sibling:
where the training loop drives a fixed number of steps over a static
dataset, this loop serves a CONTINUOUS request stream over the DVM —
requests arrive at any time, batches form at step boundaries
(continuous batching: admit up to ``infer_batch_max`` waiting requests
per step, finished requests leave immediately), and the fleet itself
grows and shrinks under load while serving.

Three planes cooperate:

- **Request plane** — :class:`RequestQueue` + :class:`Ticket`: callers
  ``submit(payload)`` and block on ``ticket.result()``; rank 0 admits
  waiting tickets at each step boundary and broadcasts the batch over
  the live window, so every rank runs the same step collectively.  A
  typed fault mid-step RE-QUEUES the in-flight batch (counted by
  ``infer_requeues``) — a request is served or requeued, never dropped
  silently.
- **Fault plane** — the same typed-fault → revoke → consensus-shrink →
  respawn → survivor-mesh pipeline as the training loop: a rank death
  degrades the fleet, not the service.  Survivors requeue the in-
  flight batch, recover to full size, and the next step serves it.
- **Elastic plane** — the FIRST closed observability→runtime loop in
  this tree: rank 0 publishes queue pressure through the SPC/metrics
  plane (``infer_requests_submitted`` − ``infer_requests_served`` =
  backlog; ``infer_queue_depth_max`` rides as a watermark), an
  operator-side :class:`LoadController` scrapes it through the DVM's
  ``metrics`` RPC, feeds a hysteresis :class:`QueueDepthPolicy`, and
  applies ``DvmClient.resize`` — which the worker-side
  :class:`~zhpe_ompi_tpu.ft.recovery.ElasticSession` the loop wraps
  picks up at the NEXT step boundary (``infer_resizes``).  Hysteresis
  (patience + cooldown) keeps an injected load step from thrashing the
  membership.

The loop contract (worker side)::

    ep = zmpi.host_init()
    ses = recovery.ElasticSession(ep)          # optional: elastic jobs
    loop = FtInferLoop(ep, infer_fn=infer, state=params, elastic=ses)
    loop.queue.submit(req)                     # rank 0, any thread
    act = loop.serve()                         # until stop/retire/halt

``infer_fn(ep, state, batch) -> (state, outputs)`` runs one collective
serving step over the CURRENT live endpoint; ``outputs`` aligns with
``batch`` and rank 0 resolves the tickets.  Rank 0 is the control
plane: ``stop()`` there broadcasts the shutdown, every other rank's
loop exits through the same step-boundary broadcast (a local stop on a
non-zero rank would diverge the collective schedule).

Hygiene: every serving thread registers in a module registry
(:func:`live_worker_threads`) and every live queue exposes its parked
tickets (:func:`parked_tickets`) — the conftest session gate asserts
both empty at teardown, so a test that leaks a serving thread or
abandons a submitted request fails the suite.
"""

from __future__ import annotations

import contextlib
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable

from ..core import errors
from ..ft import recovery
from ..mca import output as mca_output
from ..mca import var as mca_var
from ..runtime import spc

_stream = mca_output.open_stream("inferloop")

# category derivation (tools/mpit.py): the serving plane's vars and
# counters (infer_*) are one family
mca_var.register_family("infer", "infer")

mca_var.register(
    "infer_batch_max", 8,
    "Continuous-batching admission cap: rank 0 admits at most this "
    "many waiting requests per serve step (the step boundary is the "
    "admit/evict point)",
    type=int,
)
mca_var.register(
    "infer_resize_high", 8,
    "Queue-backlog high watermark of the elastic resize policy: a "
    "backlog above this votes GROW (a grow applies after "
    "infer_resize_patience consecutive votes)",
    type=int,
)
mca_var.register(
    "infer_resize_low", 1,
    "Queue-backlog low watermark of the elastic resize policy: a "
    "backlog below this votes SHRINK",
    type=int,
)
mca_var.register(
    "infer_resize_patience", 2,
    "Consecutive same-direction observations before the resize policy "
    "acts — the hysteresis half that keeps a single load spike from "
    "resizing the fleet",
    type=int,
)
mca_var.register(
    "infer_resize_cooldown", 2,
    "Observations ignored after an applied resize — the hysteresis "
    "half that keeps an in-flight membership change from compounding "
    "(grow takes effect only after the spawned ranks join)",
    type=int,
)


# -- hygiene registries (the conftest session gate's view) ---------------

_live_workers: "weakref.WeakSet[threading.Thread]" = weakref.WeakSet()
_live_queues: "weakref.WeakSet[RequestQueue]" = weakref.WeakSet()


def live_worker_threads() -> list[str]:
    """Inference serving threads still alive — must be [] once every
    loop's stop()/serve() returned (the rank-0-broadcast shutdown
    contract)."""
    return [t.name for t in list(_live_workers) if t.is_alive()]


def parked_tickets() -> list[str]:
    """Unresolved tickets still parked in live request queues — a
    drained serving plane has served, failed, or evicted every
    submitted request; an entry here is a caller wedged in
    ``result()`` forever."""
    out = []
    for q in list(_live_queues):
        out.extend(q._parked())
    return out


# -- request plane -------------------------------------------------------


class Ticket:
    """One submitted request: the caller's handle.  ``result()`` blocks
    until a serve step resolves it (or a failure/eviction raises).
    Status walks ``queued → in-flight → served`` in the good case; a
    typed fault mid-step walks it back to ``queued`` (requeued, never
    silently dropped)."""

    def __init__(self, payload: Any):
        self.payload = payload
        self.status = "queued"
        self.requeues = 0
        self._event = threading.Event()
        self._value: Any = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None) -> Any:
        if not self._event.wait(timeout):
            raise errors.InternalError(
                f"inference ticket not served within {timeout}s "
                f"(status {self.status})")
        if self._error is not None:
            raise self._error
        return self._value

    # loop-side transitions (rank 0 only)
    def _serve(self, value: Any) -> None:
        self.status = "served"
        self._value = value
        self._event.set()

    def _fail(self, exc: BaseException, status: str = "failed") -> None:
        self.status = status
        self._error = exc
        self._event.set()


class RequestQueue:
    """Thread-safe FIFO between callers and the serving loop.  Callers
    submit from any thread; rank 0's serve step takes a batch at the
    step boundary.  Requeued batches go back to the FRONT in order —
    a fault must not reorder a caller behind later arrivals."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items: deque[Ticket] = deque()
        self._inflight: set[Ticket] = set()
        self._closed = False
        _live_queues.add(self)

    def submit(self, payload: Any) -> Ticket:
        t = Ticket(payload)
        with self._lock:
            if self._closed:
                raise errors.UnsupportedError(
                    "request queue is closed (serving loop shut down)")
            self._items.append(t)
        spc.record("infer_requests_submitted")
        return t

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def take(self, max_n: int) -> list[Ticket]:
        """Admit up to ``max_n`` waiting tickets (the step boundary)."""
        out: list[Ticket] = []
        with self._lock:
            while self._items and len(out) < max(0, int(max_n)):
                t = self._items.popleft()
                t.status = "in-flight"
                self._inflight.add(t)
                out.append(t)
        return out

    def served(self, tickets: list[Ticket], values: list[Any]) -> None:
        with self._lock:
            for t in tickets:
                self._inflight.discard(t)
        for t, v in zip(tickets, values):
            t._serve(v)

    def requeue(self, tickets: list[Ticket]) -> None:
        """A typed fault interrupted the step: the batch goes back to
        the queue head, LOUDLY counted — served or requeued, never
        silently dropped."""
        if not tickets:
            return
        with self._lock:
            for t in reversed(tickets):
                self._inflight.discard(t)
                t.status = "queued"
                t.requeues += 1
                self._items.appendleft(t)
        spc.record("infer_requeues", len(tickets))
        mca_output.verbose(
            1, _stream, "requeued %d in-flight request(s) after a "
            "typed fault", len(tickets),
        )

    def abort(self, exc: BaseException | None = None) -> None:
        """Close the queue and fail everything still parked — the
        shutdown path that keeps :func:`parked_tickets` clean when a
        test tears a loop down with requests outstanding."""
        with self._lock:
            self._closed = True
            parked = list(self._items) + list(self._inflight)
            self._items.clear()
            self._inflight.clear()
        for t in parked:
            t._fail(exc or errors.UnsupportedError(
                "serving loop shut down before this request was "
                "served"), status="evicted")

    def _parked(self) -> list[str]:
        with self._lock:
            return [
                f"ticket:{t.status}:{t.payload!r:.40}"
                for t in list(self._items) + list(self._inflight)
                if not t.done()
            ]


# -- elastic policy (the observability→runtime half) ---------------------


class QueueDepthPolicy:
    """Hysteresis resize policy keyed on request-queue backlog.  A
    backlog above ``high`` for ``patience`` consecutive observations
    grows the fleet by ``step``; below ``low`` shrinks it; ``cooldown``
    observations after an applied resize are ignored so an in-flight
    membership change never compounds.  :meth:`decide` degrades
    loudly and never raises (ZL008): malformed observations vote
    nothing."""

    def __init__(self, *, high: int | None = None, low: int | None = None,
                 patience: int | None = None, cooldown: int | None = None,
                 min_size: int = 1, max_size: int | None = None,
                 step: int = 1):
        def _var(v, name, dflt):
            if v is not None:
                return int(v)
            try:
                return int(mca_var.get(name, dflt))
            except (TypeError, ValueError):
                return dflt
        self.high = _var(high, "infer_resize_high", 8)
        self.low = _var(low, "infer_resize_low", 1)
        self.patience = max(1, _var(patience, "infer_resize_patience", 2))
        self.cooldown = max(0, _var(cooldown, "infer_resize_cooldown", 2))
        self.min_size = max(1, int(min_size))
        self.max_size = None if max_size is None else int(max_size)
        self.step = max(1, int(step))
        self._grow_votes = 0
        self._shrink_votes = 0
        self._cool = 0

    def decide(self, backlog: Any, live: Any) -> int | None:
        """One observation → a target size, or None (hold).  Never
        raises: an unparseable observation resets nothing and votes
        nothing (the scrape retries next tick)."""
        try:
            backlog = int(backlog)
            live = int(live)
        except (TypeError, ValueError):
            mca_output.verbose(
                2, _stream, "resize policy: unparseable observation "
                "(backlog=%r live=%r); holding", backlog, live,
            )
            return None
        if self._cool > 0:
            self._cool -= 1
            self._grow_votes = self._shrink_votes = 0
            return None
        if backlog > self.high:
            self._grow_votes += 1
            self._shrink_votes = 0
        elif backlog < self.low:
            self._shrink_votes += 1
            self._grow_votes = 0
        else:
            self._grow_votes = self._shrink_votes = 0
        cap = self.max_size if self.max_size is not None else live
        if self._grow_votes >= self.patience and live < cap:
            self._grow_votes = self._shrink_votes = 0
            self._cool = self.cooldown
            return min(live + self.step, cap)
        if self._shrink_votes >= self.patience and live > self.min_size:
            self._grow_votes = self._shrink_votes = 0
            self._cool = self.cooldown
            return max(live - self.step, self.min_size)
        return None


class LoadController:
    """Operator-side half of the closed loop: scrape the job's
    published SPC snapshots through the DVM's ``metrics`` RPC, derive
    the backlog gauge from two monotone counters
    (``infer_requests_submitted`` − ``infer_requests_served`` — the
    Prometheus counter-difference idiom; the watermark alone cannot
    observe load FALLING), feed the policy, and apply
    ``DvmClient.resize``.  One :meth:`tick` per control interval."""

    def __init__(self, client, job_id: str,
                 policy: QueueDepthPolicy | None = None,
                 resize_timeout: float = 60.0):
        self.client = client
        self.job_id = str(job_id)
        self.policy = policy if policy is not None else QueueDepthPolicy()
        self.resize_timeout = float(resize_timeout)
        self.applied: list[dict] = []

    def observe(self) -> tuple[int, int] | None:
        """(backlog, live) from the metrics + stat RPCs, or None when
        the job has not published yet (the scrape retries)."""
        try:
            agg = self.client.metrics(self.job_id)["aggregate"]
            jobs = self.client.stat().get("jobs") or {}
            live = int((jobs.get(self.job_id) or {}).get("live") or 0)
        except errors.MpiError as e:
            mca_output.verbose(
                2, _stream, "load controller: scrape failed (%s); "
                "holding", e,
            )
            return None
        if not live:
            return None
        backlog = int(agg.get("infer_requests_submitted", 0)) \
            - int(agg.get("infer_requests_served", 0))
        return backlog, live

    def tick(self) -> dict | None:
        """One control interval: observe → decide → resize.  Returns
        the applied resize event, or None (held)."""
        obs = self.observe()
        if obs is None:
            return None
        backlog, live = obs
        target = self.policy.decide(backlog, live)
        if target is None or target == live:
            return None
        mca_output.verbose(
            1, _stream, "load controller: backlog %d over %d live "
            "rank(s) -> resize to %d", backlog, live, target,
        )
        evt = self.client.resize(self.job_id, target,
                                 timeout=self.resize_timeout)
        self.applied.append(evt)
        return evt


# -- the serving loop ----------------------------------------------------


class FtInferLoop:
    """See the module docstring for the contract."""

    def __init__(self, proc, *, infer_fn: Callable, state: Any,
                 queue: RequestQueue | None = None,
                 batch_max: int | None = None, elastic=None,
                 probe=None, prober=None, wedge=None,
                 respawner: Callable | None = None,
                 remesh_fn: Callable | None = None,
                 rejoin_timeout: float = 30.0, idle_wait: float = 0.02):
        if getattr(proc, "ft_state", None) is None:
            raise errors.UnsupportedError(
                "FtInferLoop needs fault tolerance enabled (ft=True)")
        self.proc = proc
        self.infer_fn = infer_fn
        self.state = state
        self.queue = queue if queue is not None else RequestQueue()
        self.batch_max = int(batch_max) if batch_max is not None \
            else int(mca_var.get("infer_batch_max", 8))
        self.elastic = elastic
        self.probe = probe
        self.prober = prober
        self.wedge = wedge
        self.respawner = respawner
        self.remesh_fn = remesh_fn
        self.rejoin_timeout = float(rejoin_timeout)
        self.idle_wait = float(idle_wait)
        self.served = 0
        self.steps = 0
        self.resizes = 0
        self.recoveries = 0
        self.error: BaseException | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if probe is not None and probe.on_fault is None:
            probe.on_fault = self._on_device_fault
        # traffic rides a generation-windowed dense endpoint, exactly
        # the FtTrainLoop/ElasticSession contract: an elastic loop
        # adopts the session's live window (ONE constructor shrink —
        # a second would desync the agreement counters)
        if elastic is not None:
            self.live = elastic.live
        else:
            shrink = getattr(proc, "shrink", None)
            self.live = shrink() if callable(shrink) else proc

    # -- device-fault plumbing (FtTrainLoop's hook, verbatim contract) ---

    def _on_device_fault(self, fault: errors.DeviceFault) -> None:
        flood = getattr(self.proc, "flood_device_fault", None)
        if flood is not None:
            flood(fault)
        if self.wedge is not None:
            self.wedge.release(fault)

    def _guard(self):
        inner = self.probe.guard() if self.probe is not None \
            else contextlib.nullcontext()
        if self.prober is not None:
            return self.prober.region(inner)
        return inner

    # -- one collective serve step ---------------------------------------

    def serve_step(self) -> str:
        """One continuous-batching step, collective over ``live``:
        rank 0 admits a batch (and publishes queue pressure), everyone
        adopts it through the step-boundary broadcast, the collective
        ``infer_fn`` serves it, rank 0 resolves the tickets, and the
        elastic boundary applies any pending resize.  Returns one of
        ``served | idle | stopped | resized | recovered | retire |
        halt``."""
        tickets: list[Ticket] = []
        cmd = "serve"
        if self.live.rank == 0:
            if self._stop.is_set():
                cmd = "stop"
            else:
                spc.record("infer_queue_depth_max", self.queue.depth())
                tickets = self.queue.take(self.batch_max)
        try:
            cmd, batch = self.live.bcast(
                (cmd, [t.payload for t in tickets])
                if self.live.rank == 0 else None, root=0)
            if cmd == "stop":
                return "stopped"
            outputs: list[Any] | None = None
            if batch:
                with self._guard():
                    if self.wedge is not None:
                        self.wedge.tick()
                    self.state, outputs = self.infer_fn(
                        self.live, self.state, batch)
            self.steps += 1
            if self.live.rank == 0 and tickets:
                self.queue.served(tickets, list(outputs or ()))
                self.served += len(tickets)
                spc.record("infer_requests_served", len(tickets))
        except errors.DeviceFault as e:
            if self.proc.rank in e.failed_ranks:
                raise  # THIS rank is the corpse: no survivor act
            self.queue.requeue(tickets)
            self._recover()
            return "recovered"
        except (errors.ProcFailed, errors.ProcFailedPending,
                errors.Revoked):
            self.queue.requeue(tickets)
            self._recover()
            return "recovered"
        if self.elastic is not None:
            act = self.elastic.step()  # the COLLECTIVE resize boundary
            if act in ("retire", "halt"):
                return act
            if act == "resized":
                self.resizes += 1
                spc.record("infer_resizes")
                self.live = self.elastic.live
                if self.remesh_fn is not None:
                    self.remesh_fn(self.live, self.state)
                return "resized"
        return "served" if batch else "idle"

    def serve(self, max_steps: int | None = None) -> str:
        """Serve until rank 0 stops the fleet, a resize retires this
        rank, the job halts, or ``max_steps`` boundaries pass (every
        rank counts the same boundaries — the step is collective).
        Returns the final action."""
        if self.prober is not None:
            self.prober.start()
        act = "idle"
        try:
            while max_steps is None or self.steps < max_steps:
                act = self.serve_step()
                if act in ("stopped", "retire", "halt"):
                    break
                if act == "idle":
                    time.sleep(self.idle_wait)  # uniform: the empty
                    # batch came off the broadcast, so every rank idles
                    # the same boundary
        finally:
            if self.prober is not None:
                self.prober.stop()
        if act in ("stopped", "halt"):
            # shutdown is an EVICT boundary: anything still queued is
            # failed loudly (status "evicted"), never left parked — a
            # waiter unwedges with a typed error, and the conftest
            # parked-ticket gate stays clean
            self.queue.abort()
        return act

    # -- background serving (the worker-thread surface) ------------------

    def start(self) -> None:
        """Serve on a background thread (registered for the conftest
        leak gate); ``stop()`` on rank 0 shuts the whole fleet down
        through the step-boundary broadcast."""
        if self._thread is not None and self._thread.is_alive():
            raise errors.UnsupportedError("serving thread already runs")
        self._stop.clear()
        t = threading.Thread(
            target=self._serve_bg,
            name=f"infer-serve-r{getattr(self.proc, 'rank', '?')}",
            daemon=True)
        self._thread = t
        _live_workers.add(t)
        t.start()

    def _serve_bg(self) -> None:
        try:
            self.serve()
        except BaseException as e:  # surfaced to join(), never lost
            self.error = e
            # the serving thread is dead: unwedge every waiter with
            # the same error instead of leaving tickets parked
            self.queue.abort(e)

    def stop(self, timeout: float = 30.0) -> None:
        """Request shutdown and join the serving thread.  Meaningful
        on rank 0 (the control plane broadcasts the stop); other
        ranks' threads exit through the same broadcast — join only."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise errors.InternalError(
                    "inference serving thread failed to stop within "
                    f"{timeout}s")
            self._thread = None
        if self.error is not None:
            raise self.error

    # -- recovery (the FtTrainLoop pipeline, minus checkpoint rollback) --

    def _recover(self) -> None:
        with (self.prober.region() if self.prober is not None
              else contextlib.nullcontext()):
            self._recover_inner()

    def _recover_inner(self) -> None:
        if self.respawner is None:
            raise errors.UnsupportedError(
                "FtInferLoop: a typed fault arrived with no respawner "
                "configured — pass respawner=recovery.daemon_respawn "
                "(DVM jobs) or a thread-plane respawn loop")
        self.recoveries += 1
        mca_output.verbose(
            1, _stream, "rank %d: typed fault; entering recovery %d "
            "(in-flight batch requeued)", self.proc.rank,
            self.recoveries,
        )
        revoke = getattr(self.live, "revoke", None)
        if callable(revoke):
            try:
                from ..coll import host as coll_host

                revoke(coll_host.COLL_CID)
            except errors.MpiError:
                pass

        def rollback_fn(shrunk):
            # the survivor-mesh leg: no checkpoint to roll back (the
            # request plane re-queued the batch); re-broadcast the
            # serving state onto the survivor mesh
            if self.remesh_fn is not None:
                self.remesh_fn(shrunk, self.state)

        shrunk, victims = recovery.respawn_victims(
            self.proc, self.respawner, rollback_fn=rollback_fn,
            timeout=self.rejoin_timeout)
        for v in victims:
            if not recovery.await_rejoin(self.proc, v,
                                         self.rejoin_timeout):
                raise errors.InternalError(
                    f"recovery: rank {v} never rejoined within "
                    f"{self.rejoin_timeout}s")
        state = self.proc.ft_state
        state.raise_epoch(state.crash_epoch() + 1)
        from ..coll import han as han_mod

        han_mod.invalidate(self.proc)
        self.live = self.proc.shrink()
        if self.elastic is not None:
            # keep the session's window in lockstep: its next step()
            # must ride the post-recovery membership
            self.elastic.live = self.live
        if self.remesh_fn is not None:
            self.remesh_fn(self.live, self.state)
