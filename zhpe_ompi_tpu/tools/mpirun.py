"""``zmpirun`` — the mpirun/PRRTE analog for the host plane.

In the reference, ``mpirun`` is literally a symlink to the external ``prte``
binary (``ompi/tools/mpirun/Makefile.am:11-15``): PRRTE launches the
processes, forwards their stdio (IOF), hands each proc its rank and the
PMIx contact info through the environment, propagates exit codes, and
tears the whole job down when any rank aborts
(``test/simple/delayed_abort.c`` is the acceptance shape for that).

This CLI is that surface for the TCP/DCN plane:

- **launch**: spawn ``-n`` local processes with the ``ZMPI_*`` environment
  contract (the PMIx-put/get analog) shared with the C ABI shim
  (``native/zompi_mpi.cpp`` reads the same four variables), so both Python
  ranks (via :func:`host_init`) and compiled C ranks (via the shim's
  ``MPI_Init``) join the same wire-up protocol.
- **IOF**: children's stdout/stderr are line-forwarded with a ``[r]``
  prefix (mpirun ``--tag-output`` semantics, on by default).
- **abort**: if any rank exits nonzero the remaining ranks are terminated
  after a short grace period and the job exits with the failing rank's
  code — MPI_Abort job semantics.
- **--mca name value** is forwarded as ``ZMPI_MCA_<name>`` env, exactly
  the reference's ``mpirun --mca`` → ``OMPI_MCA_*`` plumbing.

The rendezvous port is chosen by the launcher (bind-probe then release);
rank 0 re-binds it as the modex coordinator — the same fixed-port scheme
the C ABI interop tests use.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any

_TERM_GRACE = 2.0  # seconds between SIGTERM and SIGKILL on abort


class _JobSignal(Exception):
    """Raised out of the CLI's SIGINT/SIGTERM handler into the monitor
    loop: the launcher forwards the signal to the job, reaps every
    child, releases its rendezvous/name-server ports, and exits
    ``128 + signum`` — a Ctrl-C must never orphan ranks still holding
    sockets and /dev/shm rings."""

    def __init__(self, signum: int):
        super().__init__(f"signal {signum}")
        self.signum = signum


def _start_coordinator(host: str, size: int, timeout: float):
    """Host the modex rendezvous in the LAUNCHER (PRRTE hosts the PMIx
    server, ranks are all clients).  Binding port 0 here removes the
    probe-then-rebind race a launcher-chosen fixed port would have: the
    socket is listening before any rank spawns.  Every rank — including
    rank 0, told by ZMPI_COORD_EXTERNAL=1 — connects, sends its
    (rank, address) card, and receives the full address book."""
    from ..pt2pt.tcp import _recv_frame, _send_frame
    from ..utils import dss

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, 0))
    srv.listen(size + 4)
    srv.settimeout(timeout)

    def serve():
        book = [None] * size
        conns = []
        try:
            for _ in range(size):
                conn, _ = srv.accept()
                [rank, addr] = dss.unpack(_recv_frame(conn))
                book[rank] = addr
                conns.append(conn)
            payload = dss.pack(book)
            for c in conns:
                _send_frame(c, payload)
        except OSError:
            pass  # job died / timed out; ranks see their own modex timeout
        finally:
            for c in conns:
                c.close()
            srv.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    # the socket is returned alongside the port so the launcher can
    # RELEASE it on any exit path (signal teardown included): a port
    # held by a dead job's rendezvous thread is a leak
    return srv.getsockname()[1], srv


def _start_name_server(host: str):
    """The ompi-server analog: a tiny publish/lookup/unpublish registry
    that lives for the job (MPI_Publish_name needs a server that outlasts
    any one rank — the reference ships a separate ``ompi-server`` daemon
    for exactly this; here the launcher hosts it).  One request per
    connection: request frame = dss.pack of ONE list value —
    ["pub", service, port] / ["look", service] / ["unpub", service];
    reply frame = dss.pack of ONE result value (True, the port name or
    None, found-bool respectively)."""
    from ..pt2pt.tcp import _recv_frame, _send_frame
    from ..utils import dss

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, 0))
    srv.listen(16)
    registry: dict[str, str] = {}
    reg_lock = threading.Lock()

    def serve():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return  # launcher exiting
            try:
                # a stalled/garbage client must cost at most 5s and never
                # kill the service for the rest of the job
                conn.settimeout(5.0)
                frame = _recv_frame(conn)
                if frame is None:
                    continue
                [req] = dss.unpack(frame)
                op = req[0]
                with reg_lock:
                    if op == "pub":
                        registry[req[1]] = req[2]
                        out = True
                    elif op == "look":
                        out = registry.get(req[1])
                    elif op == "unpub":
                        out = registry.pop(req[1], None) is not None
                    else:
                        out = None
                _send_frame(conn, dss.pack(out))
            except Exception:  # noqa: BLE001 - malformed request; serve on
                pass
            finally:
                conn.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return srv, srv.getsockname()[1]


def _forward(stream, rank: int, label: str, out, lock: threading.Lock,
             tag: bool) -> None:
    """IOF drain thread: line-forward a child stream with a rank prefix."""
    for line in iter(stream.readline, ""):
        with lock:
            if tag:
                out.write(f"[{rank}{label}] {line}")
            else:
                out.write(line)
            out.flush()
    stream.close()


def build_env(rank: int, size: int, host: str, port: int,
              mca: list[tuple[str, str]] | None = None,
              ns_port: int | None = None, ft: bool = False) -> dict:
    """The ZMPI_* environment contract one rank sees (PMIx envars analog)."""
    env = dict(os.environ)
    env.update({
        "ZMPI_RANK": str(rank),
        "ZMPI_SIZE": str(size),
        "ZMPI_COORD_HOST": host,
        "ZMPI_COORD_PORT": str(port),
        # the launcher hosts the rendezvous: rank 0 joins as a client
        # instead of binding the coordinator itself
        "ZMPI_COORD_EXTERNAL": "1",
        # session tag for /dev/shm segment names: INHERITED by
        # MPI_Comm_spawn children (whose coordinator port differs), so
        # the launcher's end-of-job sweep catches every segment of the
        # whole job tree with one prefix
        "ZMPI_SESSION": str(port),
    })
    if ns_port is not None:
        env["ZMPI_NAMESERVER"] = f"{host}:{ns_port}"
    if ft:
        # fault-tolerant job: every rank's host_init builds an ft=True
        # endpoint (detector, typed failures, recovery surface)
        env["ZMPI_FT"] = "1"
    # make the framework importable in every rank regardless of cwd — the
    # mpirun-exports-its-library-paths behavior (OPAL_PREFIX/LD_LIBRARY_PATH)
    pkg_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))
    parts = env.get("PYTHONPATH", "").split(os.pathsep)
    if pkg_root not in parts:
        env["PYTHONPATH"] = os.pathsep.join([pkg_root] + [p for p in parts if p])
    for name, value in mca or ():
        env[f"ZMPI_MCA_{name}"] = value
    return env


def launch(n: int, argv: list[str], host: str = "127.0.0.1",
           mca: list[tuple[str, str]] | None = None,
           timeout: float | None = None, tag_output: bool = True,
           stdout=None, stderr=None, ft: bool = False) -> int:
    """Run ``argv`` as an ``n``-rank job; returns the job exit code.

    Python programs (``*.py``) run under the current interpreter; anything
    else is exec'd directly (a C program linked against the ABI shim).
    """
    return launch_mpmd([(n, argv)], host=host, mca=mca, timeout=timeout,
                       tag_output=tag_output, stdout=stdout, stderr=stderr,
                       ft=ft)


def launch_dvm(dvm: str, n: int, argv: list[str] | None = None,
               mca: list[tuple[str, str]] | None = None,
               timeout: float | None = None, tag_output: bool = True,
               stdout=None, stderr=None, ft: bool = False,
               metrics: bool = False, trace: bool = False,
               max_size: int | None = None,
               apps: list[tuple[int, list[str]]] | None = None,
               priority: int = 0,
               placement: str | None = None) -> int:
    """Launch a job INTO a resident runtime daemon (``zmpirun --dvm``):
    the zprted VM hosts the PMIx store and the children, streams their
    IOF back here, and outlives the job — no per-job rendezvous, no
    name server, no launcher teardown (the prte DVM shape;
    :mod:`zhpe_ompi_tpu.runtime.dvm`).  On a DVM *tree* the target may
    be any daemon, but launches go to the root (``zmpirun --dvm`` users
    pass the root's address); ranks are block-placed across the tree's
    hosts.  ``metrics=True`` exports ``ZMPI_METRICS=1`` to every rank:
    each publishes SPC snapshots into the resident store (the
    fleet-visible metrics plane).  ``max_size`` (> n) launches the job
    ELASTIC (see :meth:`DvmClient.launch`); ``apps`` is the MPMD form —
    mixed C/Python contexts share the store-served wire-up.
    ``priority`` orders this launch in the daemon's admission queue
    (``dvm_admission_policy=priority``); ``placement`` picks its
    subtree policy (pack/spread/exclusive, default the daemon's
    ``dvm_placement``)."""
    from ..runtime.dvm import DvmClient

    client = DvmClient(dvm)
    try:
        return client.launch(n, argv, mca=mca, ft=ft, timeout=timeout,
                             tag_output=tag_output, stdout=stdout,
                             stderr=stderr, metrics=metrics,
                             trace=trace, max_size=max_size, apps=apps,
                             priority=priority, placement=placement)
    finally:
        client.close()


def resize_dvm(dvm: str, job_id: str, n: int,
               timeout: float = 60.0) -> dict:
    """Elastic resize of a running ft job in the resident VM
    (``zmpirun --dvm H:P --resize JOB -n N``): grow spawns fresh ranks
    that FT_JOIN the live job, shrink retires the highest live ranks
    through the orderly-BYE path.  Returns the applied event."""
    from ..runtime.dvm import DvmClient

    client = DvmClient(dvm)
    try:
        return client.resize(job_id, n, timeout=timeout)
    finally:
        client.close()


def launch_mpmd(apps: list[tuple[int, list[str]]], host: str = "127.0.0.1",
                mca: list[tuple[str, str]] | None = None,
                timeout: float | None = None, tag_output: bool = True,
                stdout=None, stderr=None, ft: bool = False) -> int:
    """MPMD launch (mpirun's ``-n A progA : -n B progB``): one job, one
    COMM_WORLD, consecutive rank blocks per app context.  Mixed
    Python/C contexts share the wire protocol, so a C ring and a Python
    analytics rank can be one job."""
    if not apps or any(n < 1 for n, _ in apps):
        raise ValueError("zmpirun: every app context needs -n >= 1")
    n = sum(cnt for cnt, _ in apps)
    stdout = stdout if stdout is not None else sys.stdout
    stderr = stderr if stderr is not None else sys.stderr
    port, coord_srv = _start_coordinator(host, n, timeout or 120.0)
    ns_srv, ns_port = _start_name_server(host)
    cmds: list[list[str]] = []
    for cnt, argv in apps:
        cmd = list(argv)
        if cmd[0].endswith(".py"):
            cmd = [sys.executable] + cmd
        cmds.extend([cmd] * cnt)
    try:
        return _launch_job(n, cmds, host, port, ns_port, mca, timeout,
                           tag_output, stdout, stderr, ft)
    finally:
        # release the ports on EVERY exit path (signal teardown
        # included): the rendezvous and name-server sockets must not
        # outlive the job they served
        coord_srv.close()
        ns_srv.close()  # stops the name-server accept loop
        _sweep_session_shm(port)


def _sweep_session_shm(port: int) -> None:
    """The PRRTE session-directory cleanup analog: a rank that aborts
    (or is killed) never reaches MPI_Finalize, so its /dev/shm ring and
    shared-window segments survive it.  Every segment of the job TREE
    embeds the launcher's session tag (ZMPI_SESSION, inherited through
    MPI_Comm_spawn whose children rendezvous on a different port), so
    one prefix sweep covers spawned ranks too."""
    try:
        for f in os.listdir("/dev/shm"):
            if f.startswith(f"zompi_ring_{port}_") or \
                    f.startswith(f"zompi_shm_{port}_") or \
                    f.startswith(f"zompi_pyring_{port}_"):
                try:
                    os.unlink(os.path.join("/dev/shm", f))
                except OSError:
                    pass
    except OSError:
        pass  # /dev/shm absent: nothing to sweep


def _launch_job(n, cmds, host, port, ns_port, mca, timeout, tag_output,
                stdout, stderr, ft: bool = False) -> int:
    procs: list[subprocess.Popen] = []
    drains: list[threading.Thread] = []
    out_lock = threading.Lock()
    live: set = set()
    deadline = time.monotonic() + timeout if timeout else None
    exit_code = 0
    failed_rank = None
    # the spawn loop sits INSIDE the signal-handling try: a SIGTERM
    # landing mid-spawn must tear down the ranks already started, not
    # orphan them in the modex rendezvous (children run in their own
    # sessions — the terminal's signal never reaches them directly)
    try:
        for rank in range(n):
            try:
                p = subprocess.Popen(
                    cmds[rank],
                    env=build_env(rank, n, host, port, mca, ns_port, ft),
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True,
                    start_new_session=True,  # isolate from our signals
                )
            except OSError:
                # MPMD makes mid-loop spawn failure real (a later
                # context's binary may be missing): don't orphan
                # already-spawned ranks in the modex rendezvous
                _teardown(procs, set(live))
                raise
            procs.append(p)
            live.add(rank)
            for stream, label, sink in (
                (p.stdout, "", stdout), (p.stderr, ":err", stderr),
            ):
                t = threading.Thread(
                    target=_forward,
                    args=(stream, rank, label, sink, out_lock,
                          tag_output),
                    daemon=True,
                )
                t.start()
                drains.append(t)

        while live:
            for rank in sorted(live):
                rc = procs[rank].poll()
                if rc is None:
                    continue
                live.discard(rank)
                if rc != 0 and failed_rank is None:
                    failed_rank, exit_code = rank, rc
            if failed_rank is not None and live:
                # MPI_Abort job teardown: one rank failed, kill the rest
                with out_lock:
                    stderr.write(
                        f"zmpirun: rank {failed_rank} exited with code "
                        f"{exit_code}; terminating {len(live)} remaining "
                        "rank(s)\n"
                    )
                    stderr.flush()
                _teardown(procs, live)
                break
            if deadline is not None and time.monotonic() > deadline:
                with out_lock:
                    stderr.write(
                        f"zmpirun: job timeout after {timeout}s; killing "
                        f"{len(live)} rank(s)\n"
                    )
                    stderr.flush()
                _teardown(procs, live)
                exit_code = 124
                break
            time.sleep(0.02)
    except KeyboardInterrupt:
        # Ctrl-C without the CLI's handlers installed (library callers):
        # same hygiene, conventional 130 = 128 + SIGINT
        _forward_signal(procs, live, signal.SIGINT)
        _teardown(procs, live)
        exit_code = 130
    except _JobSignal as js:
        # the CLI's SIGINT/SIGTERM handler: forward the ACTUAL signal to
        # the job first (ranks may catch it and finalize), then the
        # TERM→KILL reaping ladder, then exit 128+sig
        with out_lock:
            stderr.write(
                f"zmpirun: caught signal {js.signum}; forwarding to "
                f"{len(live)} rank(s) and exiting\n"
            )
            stderr.flush()
        _forward_signal(procs, live, js.signum)
        _teardown(procs, live)
        exit_code = 128 + js.signum
    for t in drains:
        t.join(timeout=2.0)
    return exit_code


def _forward_signal(procs: list[subprocess.Popen], live: set,
                    signum: int) -> None:
    for rank in list(live):
        p = procs[rank]
        if p.poll() is None:
            try:
                os.killpg(os.getpgid(p.pid), signum)
            except (OSError, ProcessLookupError):
                pass


def _teardown(procs: list[subprocess.Popen], live: set) -> None:
    for rank in list(live):
        p = procs[rank]
        if p.poll() is None:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGTERM)
            except (OSError, ProcessLookupError):
                pass
    grace_end = time.monotonic() + _TERM_GRACE
    for rank in list(live):
        p = procs[rank]
        try:
            p.wait(timeout=max(0.0, grace_end - time.monotonic()))
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass
            p.wait()
        live.discard(rank)


def main(args: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="zmpirun",
        description="Launch an n-rank host-plane job (mpirun analog). "
                    "MPMD: separate app contexts with ':' — "
                    "zmpirun -n 2 progA : -n 2 progB",
    )
    ap.add_argument("-n", "--np", type=int, required=True, dest="n",
                    help="number of ranks (per app context)")
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind/rendezvous address (default 127.0.0.1)")
    ap.add_argument("--mca", nargs=2, action="append", default=[],
                    metavar=("NAME", "VALUE"),
                    help="set an MCA variable (forwarded as ZMPI_MCA_NAME)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="kill the job after this many seconds")
    ap.add_argument("--no-tag-output", action="store_true",
                    help="forward child output without [rank] prefixes")
    ap.add_argument("--dvm", default=None, metavar="HOST:PORT",
                    help="launch into a resident zprted daemon instead "
                         "of cold-spawning (python -m "
                         "zhpe_ompi_tpu.runtime.dvm starts one; on a "
                         "daemon TREE pass the root's address)")
    ap.add_argument("--max-size", type=int, default=None,
                    help="elastic job (--dvm + --ft only): the "
                         "endpoint universe is this many slots, -n of "
                         "them start live, and the daemon's resize RPC "
                         "grows/shrinks membership while the job runs")
    ap.add_argument("--priority", type=int, default=0,
                    help="admission priority (--dvm only): higher "
                         "admits first when the daemon runs "
                         "dvm_admission_policy=priority; ties admit "
                         "in arrival order")
    ap.add_argument("--placement", default=None,
                    choices=("pack", "spread", "exclusive"),
                    help="subtree placement policy (--dvm only): "
                         "pack = block-fill the attach order, spread "
                         "= least-loaded daemons first, exclusive = "
                         "claim daemons hosting no other live job "
                         "(falls back to spread, loudly, when none "
                         "are free); default the daemon's "
                         "dvm_placement")
    ap.add_argument("--resize", default=None, metavar="JOB",
                    help="resize a RUNNING elastic job in the resident "
                         "VM to -n live ranks (--dvm only; no program "
                         "argument) and print the applied event")
    ap.add_argument("--ft", action="store_true",
                    help="fault-tolerant job: ranks build ft=True "
                         "endpoints (detector, typed failures, daemon "
                         "fault events under --dvm)")
    ap.add_argument("--metrics", action="store_true",
                    help="metrics plane (--dvm only): every rank "
                         "publishes its SPC counters into the resident "
                         "store (ZMPI_METRICS=1), scrapeable via the "
                         "daemon's metrics RPC / --metrics-port")
    ap.add_argument("--trace", action="store_true",
                    help="tracing plane (--dvm only, implies "
                         "--metrics): every rank records causal spans "
                         "(ZMPI_TRACE=1) and publishes trace:<job>:"
                         "<rank> buffers for tools/ztrace's merged "
                         "timeline")
    ap.add_argument("argv", nargs=argparse.REMAINDER,
                    help="program and its arguments")
    raw = list(sys.argv[1:] if args is None else args)
    # MPMD: split on ':' tokens; global flags come from the FIRST context
    contexts: list[list[str]] = [[]]
    for tok in raw:
        if tok == ":":
            contexts.append([])
        else:
            contexts[-1].append(tok)
    first = ap.parse_args(contexts[0])
    if first.resize is not None:
        if not first.dvm:
            ap.error("--resize needs --dvm (the job lives in the "
                     "resident VM)")
        if first.argv or len(contexts) > 1:
            ap.error("--resize takes no program: -n is the new live "
                     "size")
        event = resize_dvm(first.dvm, first.resize, first.n,
                           timeout=first.timeout or 60.0)
        print(f"resized {event['job']} to {event['size']} "
              f"(grown={event['grown']} retired={event['retired']} "
              f"generation={event['generation']})")
        return 0
    if not first.argv:
        ap.error("no program given")
    apps = [(first.n, first.argv)]
    for extra in contexts[1:]:
        more = ap.parse_args(extra)
        if not more.argv:
            ap.error("empty app context after ':'")
        # global flags belong to the FIRST context only; accepting them
        # later and ignoring them would silently drop user intent
        if (more.host != "127.0.0.1" or more.mca or
                more.timeout is not None or more.no_tag_output or
                more.dvm or more.ft or more.metrics or more.trace or
                more.max_size is not None or more.resize is not None or
                more.priority or more.placement is not None):
            ap.error(
                "--host/--mca/--timeout/--no-tag-output/--dvm/--ft/"
                "--metrics/--trace/--max-size/--resize/--priority/"
                "--placement are job-global: pass them in the first "
                "app context"
            )
        apps.append((more.n, more.argv))
    if first.max_size is not None and not first.dvm:
        ap.error("--max-size (elastic) needs the resident VM: run "
                 "with --dvm")
    if (first.priority or first.placement is not None) and not first.dvm:
        ap.error("--priority/--placement order and place launches in "
                 "the resident VM: run with --dvm")
    # signal hygiene (main thread only — the CLI path): SIGINT/SIGTERM
    # are forwarded to the job, children reaped, ports released, exit
    # 128+sig — see _JobSignal
    restore: dict[int, Any] = {}

    def _on_signal(signum, _frame):
        raise _JobSignal(signum)

    if threading.current_thread() is threading.main_thread():
        for s in (signal.SIGINT, signal.SIGTERM):
            restore[s] = signal.signal(s, _on_signal)
    try:
        if first.dvm:
            return launch_dvm(
                first.dvm, first.n,
                first.argv if len(apps) == 1 else None,
                mca=[tuple(m) for m in first.mca],
                timeout=first.timeout,
                tag_output=not first.no_tag_output, ft=first.ft,
                metrics=first.metrics or first.trace,
                trace=first.trace, max_size=first.max_size,
                apps=None if len(apps) == 1 else apps,
                priority=first.priority, placement=first.placement,
            )
        if first.metrics or first.trace:
            ap.error("--metrics/--trace need the resident store: run "
                     "with --dvm")
        return launch_mpmd(
            apps, host=first.host, mca=[tuple(m) for m in first.mca],
            timeout=first.timeout, tag_output=not first.no_tag_output,
            ft=first.ft,
        )
    except _JobSignal as js:
        # a signal that landed outside the monitor loop (teardown
        # already ran, or the job never started): same exit contract
        return 128 + js.signum
    finally:
        for s, h in restore.items():
            signal.signal(s, h)


if __name__ == "__main__":
    sys.exit(main())
