/* io2_c.c — round-5 MPI-IO tier-2 acceptance: file views (strided
 * filetype tiling), collective and split collective IO, shared-pointer
 * IO (independent + ordered), nonblocking IO, preallocate/atomicity,
 * byte-offset/type-extent queries.  Reference shapes:
 * ompi/mpi/c/{file_set_view,file_read_all,file_write_at_all_begin,
 * file_write_shared,file_write_ordered,file_iread,file_preallocate,
 * file_get_byte_offset}.c.  Run with >= 2 ranks. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "zompi_mpi.h"

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);  \
      MPI_Abort(MPI_COMM_WORLD, 2);                                    \
    }                                                                  \
  } while (0)

int main(int argc, char **argv) {
  CHECK(MPI_Init(&argc, &argv) == MPI_SUCCESS);
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  CHECK(size >= 2);

  char path[256];
  snprintf(path, sizeof path, "/tmp/zompi_io2_%s.bin",
           getenv("ZMPI_COORD_PORT") ? getenv("ZMPI_COORD_PORT") : "0");

  MPI_File fh;
  CHECK(MPI_File_open(MPI_COMM_WORLD, path,
                      MPI_MODE_CREATE | MPI_MODE_RDWR, MPI_INFO_NULL,
                      &fh) == MPI_SUCCESS);

  /* ---- preallocate + collective write_at_all ---- */
  CHECK(MPI_File_preallocate(fh, 4096) == MPI_SUCCESS);
  MPI_Offset fsz = -1;
  CHECK(MPI_File_get_size(fh, &fsz) == MPI_SUCCESS && fsz >= 4096);
  int mine[4];
  for (int i = 0; i < 4; i++) mine[i] = rank * 100 + i;
  MPI_Status st;
  CHECK(MPI_File_write_at_all(fh, (MPI_Offset)(rank * 16), mine, 4,
                              MPI_INT, &st) == MPI_SUCCESS);
  CHECK(st._count == 16);

  /* everyone sees everyone's block after the collective */
  int peer = (rank + 1) % size;
  int got[4] = {-1, -1, -1, -1};
  CHECK(MPI_File_read_at_all(fh, (MPI_Offset)(peer * 16), got, 4,
                             MPI_INT, &st) == MPI_SUCCESS);
  for (int i = 0; i < 4; i++) CHECK(got[i] == peer * 100 + i);

  /* ---- split collective pair ---- */
  int got2[4] = {0, 0, 0, 0};
  CHECK(MPI_File_read_at_all_begin(fh, (MPI_Offset)(rank * 16), got2, 4,
                                   MPI_INT) == MPI_SUCCESS);
  CHECK(MPI_File_read_at_all_end(fh, got2, &st) == MPI_SUCCESS);
  CHECK(st._count == 16 && got2[0] == rank * 100);

  /* ---- view: each rank sees only its stride-slice of the file ----
   * filetype = one int at offset rank, extent size ints; the file
   * becomes a rank-interleaved array.  disp skips the 4096-byte
   * preallocated header region. */
  {
    MPI_Datatype ft, rft;
    CHECK(MPI_Type_vector(1, 1, 1, MPI_INT, &ft) == MPI_SUCCESS);
    /* place my int at position `rank` within a size-int tile */
    int bl[1] = {1};
    int dp[1] = {rank};
    MPI_Datatype base;
    CHECK(MPI_Type_indexed(1, bl, dp, MPI_INT, &base) == MPI_SUCCESS);
    CHECK(MPI_Type_create_resized(base, 0, size * (int)sizeof(int),
                                  &rft) == MPI_SUCCESS);
    CHECK(MPI_Type_commit(&rft) == MPI_SUCCESS);
    CHECK(MPI_File_set_view(fh, 4096, MPI_INT, rft, "native",
                            MPI_INFO_NULL) == MPI_SUCCESS);

    /* byte offset of view element k = 4096 + (k*size + rank)*4 */
    MPI_Offset bo = -1;
    CHECK(MPI_File_get_byte_offset(fh, 2, &bo) == MPI_SUCCESS);
    CHECK(bo == 4096 + (2 * size + rank) * (MPI_Offset)sizeof(int));

    /* each rank writes 8 ints through its view (individual pointer) */
    int vals[8];
    for (int i = 0; i < 8; i++) vals[i] = rank * 1000 + i;
    CHECK(MPI_File_write_all(fh, vals, 8, MPI_INT, &st) == MPI_SUCCESS);
    CHECK(st._count == 32);
    MPI_Offset pos = -1;
    CHECK(MPI_File_get_position(fh, &pos) == MPI_SUCCESS && pos == 8);

    /* read back through the view from the start */
    CHECK(MPI_File_seek(fh, 0, MPI_SEEK_SET) == MPI_SUCCESS);
    int back[8];
    memset(back, 0, sizeof back);
    CHECK(MPI_File_read_all(fh, back, 8, MPI_INT, &st) == MPI_SUCCESS);
    for (int i = 0; i < 8; i++) CHECK(back[i] == rank * 1000 + i);

    /* the raw file really is interleaved: reset to the default view
     * and inspect a full tile */
    CHECK(MPI_File_set_view(fh, 0, MPI_BYTE, MPI_BYTE, "native",
                            MPI_INFO_NULL) == MPI_SUCCESS);
    int tile0[64];
    CHECK(MPI_File_read_at(fh, 4096, tile0, size, MPI_INT, &st) ==
          MPI_SUCCESS);
    for (int r = 0; r < size; r++) CHECK(tile0[r] == r * 1000);
    (void)tile0;
    MPI_Type_free(&ft);
    MPI_Type_free(&base);
    MPI_Type_free(&rft);
  }

  /* ---- view introspection ---- */
  {
    MPI_Offset disp = -1;
    MPI_Datatype et = -5, ft2 = -5;
    char rep[MPI_MAX_DATAREP_STRING];
    CHECK(MPI_File_get_view(fh, &disp, &et, &ft2, rep) == MPI_SUCCESS);
    CHECK(disp == 0 && et == MPI_BYTE && strcmp(rep, "native") == 0);
    MPI_Offset text = -1;
    CHECK(MPI_File_get_type_extent(fh, MPI_DOUBLE, &text) ==
          MPI_SUCCESS && text == 8);
    int at = -1;
    CHECK(MPI_File_set_atomicity(fh, 1) == MPI_SUCCESS);
    CHECK(MPI_File_get_atomicity(fh, &at) == MPI_SUCCESS && at == 1);
  }

  /* ---- shared pointer: every rank appends one stamped record; all
   * records land, none overlap ---- */
  {
    CHECK(MPI_File_seek_shared(fh, 8192 / (MPI_Offset)sizeof(char),
                               MPI_SEEK_SET) == MPI_SUCCESS);
    long long rec[2] = {0x5A5A0000LL + rank, rank};
    CHECK(MPI_File_write_shared(fh, rec, 2, MPI_LONG_LONG, &st) ==
          MPI_SUCCESS);
    MPI_Barrier(MPI_COMM_WORLD);
    MPI_Offset sp = -1;
    CHECK(MPI_File_get_position_shared(fh, &sp) == MPI_SUCCESS);
    CHECK(sp == 8192 + size * 16);
    /* validate every record appears exactly once */
    if (rank == 0) {
      long long *all = malloc((size_t)size * 16);
      CHECK(MPI_File_read_at(fh, 8192, all, 2 * size, MPI_LONG_LONG,
                             &st) == MPI_SUCCESS);
      int *seen = calloc((size_t)size, sizeof(int));
      for (int r = 0; r < size; r++) {
        long long who = all[2 * r + 1];
        CHECK(who >= 0 && who < size);
        CHECK(all[2 * r] == 0x5A5A0000LL + who);
        seen[who]++;
      }
      for (int r = 0; r < size; r++) CHECK(seen[r] == 1);
      free(all);
      free(seen);
    }
    MPI_Barrier(MPI_COMM_WORLD);
  }

  /* ---- ordered shared IO: rank order is deterministic ---- */
  {
    CHECK(MPI_File_seek_shared(fh, 16384, MPI_SEEK_SET) == MPI_SUCCESS);
    int stamp[2] = {rank, rank * 7};
    CHECK(MPI_File_write_ordered(fh, stamp, 2, MPI_INT, &st) ==
          MPI_SUCCESS);
    int all2[64];
    CHECK(MPI_File_read_at_all(fh, 16384, all2, 2 * size, MPI_INT,
                               &st) == MPI_SUCCESS);
    for (int r = 0; r < size; r++) {
      CHECK(all2[2 * r] == r); /* rank order, not arrival order */
      CHECK(all2[2 * r + 1] == r * 7);
    }
    /* ordered split pair */
    CHECK(MPI_File_seek_shared(fh, 20480, MPI_SEEK_SET) == MPI_SUCCESS);
    CHECK(MPI_File_write_ordered_begin(fh, stamp, 2, MPI_INT) ==
          MPI_SUCCESS);
    CHECK(MPI_File_write_ordered_end(fh, stamp, &st) == MPI_SUCCESS);
    CHECK(st._count == 8);
  }

  /* ---- nonblocking IO overlaps ---- */
  {
    int wbuf[4] = {rank, rank + 1, rank + 2, rank + 3};
    MPI_Request wr;
    CHECK(MPI_File_iwrite_at(fh, (MPI_Offset)(24576 + rank * 16), wbuf,
                             4, MPI_INT, &wr) == MPI_SUCCESS);
    CHECK(MPI_Wait(&wr, &st) == MPI_SUCCESS && st._count == 16);
    int rbuf[4] = {-1, -1, -1, -1};
    MPI_Request rr;
    CHECK(MPI_File_iread_at(fh, (MPI_Offset)(24576 + rank * 16), rbuf,
                            4, MPI_INT, &rr) == MPI_SUCCESS);
    CHECK(MPI_Wait(&rr, &st) == MPI_SUCCESS && st._count == 16);
    for (int i = 0; i < 4; i++) CHECK(rbuf[i] == rank + i);

    /* shared-pointer nonblocking append */
    CHECK(MPI_File_seek_shared(fh, 28672, MPI_SEEK_SET) == MPI_SUCCESS);
    MPI_Request sr;
    CHECK(MPI_File_iwrite_shared(fh, wbuf, 4, MPI_INT, &sr) ==
          MPI_SUCCESS);
    CHECK(MPI_Wait(&sr, &st) == MPI_SUCCESS && st._count == 16);
    MPI_Barrier(MPI_COMM_WORLD);
    MPI_Offset sp = -1;
    CHECK(MPI_File_get_position_shared(fh, &sp) == MPI_SUCCESS);
    CHECK(sp == 28672 + size * 16);
  }

  CHECK(MPI_File_close(&fh) == MPI_SUCCESS);
  if (rank == 0) MPI_File_delete(path, MPI_INFO_NULL);
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("io2_c OK on %d ranks\n", size);
  MPI_Finalize();
  return 0;
}
