"""Collective algorithm library — the heart of the framework.

TPU-native re-design of ``ompi/mca/coll/base`` (SURVEY.md §2.4).  Where the
reference implements each algorithm as a loop of blocking send/recv pairs
driven by the progress engine (e.g. recursive doubling at
``coll_base_allreduce.c:130``, ring at ``:341``, Rabenseifner at ``:970``;
binomial bcast at ``coll_base_bcast.c:329``; pairwise alltoall at
``coll_base_alltoall.c:132``; Bruck allgather at ``coll_base_allgather.c:85``),
here every algorithm is a *static communication schedule* traced once under
``jit``: rounds become ``lax.ppermute`` ops over the ICI mesh, per-rank
divergence becomes ``jnp.where`` masks on the traced rank, and XLA overlaps /
pipelines the rounds.  There is no matching, no fragmentation, no progress
loop — the compiler owns scheduling.

Conventions:

- all functions take ``(comm, x, ...)`` and must be called inside
  ``shard_map`` over the comm's mesh axis;
- ``x`` may be a pytree for the mask-based algorithms (MINLOC/MAXLOC pairs are
  (value, index) tuples); chunked algorithms (ring, Bruck, pairwise) require a
  single dense array;
- patterns are comm-relative and instantiated per sub-group by
  :func:`zhpe_ompi_tpu.pt2pt.spmd.global_pairs` — one XLA op carries every
  sub-communicator of a split;
- mask-based algorithms require a uniform partition (same size per group);
  the components route non-uniform comms to the XLA-native paths.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..core import errors
from ..pt2pt import spmd


def _where(mask, a, b):
    """Pytree-aware jnp.where with a scalar traced mask."""
    return jax.tree.map(lambda u, v: jnp.where(mask, u, v), a, b)


def _require_uniform(comm) -> int:
    n = comm.uniform_size
    if n is None:
        raise errors.CommError(
            "algorithmic collectives require a uniform partition; "
            "use the xla component for non-uniform splits"
        )
    return n


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1)


# ---------------------------------------------------------------------------
# Allreduce (cf. coll_base_allreduce.c)
# ---------------------------------------------------------------------------


def allreduce_recursive_doubling(comm, x, op):
    """Recursive doubling (reference: coll_base_allreduce.c:130): log2(p)
    exchange rounds; non-power-of-two handled by folding the tail into the
    leading block first (the reference's pow2 adjust at :175-185)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    p2 = _pow2_floor(n)
    extra = n - p2
    if extra:
        recv = spmd.ppermute(comm, x, [(p2 + i, i) for i in range(extra)])
        x = _where(rank < extra, op(recv, x), x)
    k = 1
    while k < p2:
        recv = spmd.ppermute(
            comm, x, [(i, i ^ k) for i in range(p2)]
        )
        x = _where(rank < p2, op(recv, x), x)
        k <<= 1
    if extra:
        recv = spmd.ppermute(comm, x, [(i, p2 + i) for i in range(extra)])
        x = _where(rank >= p2, recv, x)
    return x


def _chunked(x, n):
    """Pad-and-view a dense array as (n, chunk) plus restore info."""
    flat = x.reshape(-1)
    length = flat.shape[0]
    chunk = -(-length // n)  # ceil
    pad = n * chunk - length
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n, chunk), length


def allreduce_ring(comm, x, op):
    """Ring allreduce: reduce-scatter ring + allgather ring (reference:
    coll_base_allreduce.c:341).  Bandwidth-optimal — 2(p-1)/p of the data
    crosses each link; the shape XLA itself uses for large psums on ICI."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    if not isinstance(x, jax.Array) and not hasattr(x, "shape"):
        raise errors.ArgError("ring allreduce requires a dense array")
    rank = comm.rank()
    buf, length = _chunked(x, n)

    def rs_round(k, b):
        send_idx = (rank - k) % n
        recv_idx = (rank - k - 1) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(op(sent, jnp.take(b, recv_idx, axis=0)))

    buf = lax.fori_loop(0, n - 1, rs_round, buf)

    def ag_round(k, b):
        send_idx = (rank + 1 - k) % n
        recv_idx = (rank - k) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(sent)

    buf = lax.fori_loop(0, n - 1, ag_round, buf)
    return buf.reshape(-1)[:length].reshape(x.shape)


def allreduce_rabenseifner(comm, x, op):
    """Rabenseifner: recursive-halving reduce-scatter + recursive-doubling
    allgather (reference: coll_base_allreduce.c:970).  Power-of-two ranks;
    falls back to ring otherwise — the same guard the reference's decision
    logic applies."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return allreduce_ring(comm, x, op)
    if n == 1:
        return x
    rank = comm.rank()
    buf, length = _chunked(x, n)
    chunk = buf.shape[1]

    # reduce-scatter by recursive halving; rank ends owning chunk `rank`
    lo = jnp.zeros((), jnp.int32)
    bit = n >> 1
    while bit:
        pairs = [(i, i ^ bit) for i in range(n)]
        on_upper = (rank & bit) != 0
        send_lo = jnp.where(on_upper, lo, lo + bit)  # give away other half
        keep_lo = jnp.where(on_upper, lo + bit, lo)
        sent = spmd.ppermute(
            comm, lax.dynamic_slice(buf, (send_lo, 0), (bit, chunk)), pairs
        )
        kept = lax.dynamic_slice(buf, (keep_lo, 0), (bit, chunk))
        buf = lax.dynamic_update_slice(buf, op(sent, kept), (keep_lo, 0))
        lo = keep_lo
        bit >>= 1

    # allgather by recursive doubling
    w = 1
    while w < n:
        pairs = [(i, i ^ w) for i in range(n)]
        my_lo = rank & ~(w - 1)
        partner_lo = (rank ^ w) & ~(w - 1)
        sent = spmd.ppermute(
            comm, lax.dynamic_slice(buf, (my_lo, 0), (w, chunk)), pairs
        )
        buf = lax.dynamic_update_slice(buf, sent, (partner_lo, 0))
        w <<= 1
    return buf.reshape(-1)[:length].reshape(x.shape)


def allreduce_linear(comm, x, op):
    """Basic linear (reference: coll_base_allreduce.c:881): gather everything
    everywhere, reduce locally in strict rank order — the only algorithm
    whose reduction order matches MPI's canonical order for non-commutative
    ops (rank 0's value ⊕ rank 1's ⊕ ...)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    # stack every rank's contribution: leaf shape (n, *leaf.shape)
    gathered = jax.tree.map(
        lambda a: allgather_ring(comm, jnp.asarray(a)[None]), x
    )

    def block(i):
        return jax.tree.map(lambda g: jnp.take(g, i, axis=0), gathered)

    acc = block(0)
    for i in range(1, n):
        acc = op(acc, block(i))
    return jax.tree.map(
        lambda o, xx: o.reshape(jnp.shape(xx)), acc, x
    )


# ---------------------------------------------------------------------------
# Bcast (cf. coll_base_bcast.c)
# ---------------------------------------------------------------------------


def bcast_binomial(comm, x, root=0):
    """Binomial tree (reference: coll_base_bcast.c:329): round k, virtual
    ranks < 2^k forward to vrank+2^k."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    k = 1
    while k < n:
        pairs = []
        for v in range(min(k, n - k)):
            pairs.append((( v + root) % n, (v + k + root) % n))
        recv = spmd.ppermute(comm, x, pairs)
        x = _where((vrank >= k) & (vrank < 2 * k), recv, x)
        k <<= 1
    return x


def bcast_chain(comm, x, root=0, segments: int = 4):
    """Chain/pipeline bcast (reference: coll_base_bcast.c:273,301): the
    message is cut into segments flowing down a rank chain; XLA overlaps the
    segment ppermutes.  `segments` plays the role of the reference's segsize
    MCA param."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    flat = x.reshape(-1)
    length = flat.shape[0]
    segments = max(1, min(segments, length))
    seg = -(-length // segments)
    pad = segments * seg - length
    if pad:
        flat = jnp.pad(flat, (0, pad))
    segs = flat.reshape(segments, seg)

    # chain pattern in vrank space: v -> v+1; segment s reaches chain
    # position v at step v-1+s, so at step t position v adopts segment
    # s = t - v + 1.  All rounds are static; XLA pipelines the hops.
    pairs = [((v + root) % n, (v + 1 + root) % n) for v in range(n - 1)]
    total_steps = (n - 1) + (segments - 1)

    def step(t, sg):
        sent = spmd.ppermute(comm, sg, pairs)
        s_idx = t - vrank + 1
        adopt = (vrank > 0) & (s_idx >= 0) & (s_idx < segments)
        mask = (jnp.arange(segments) == s_idx) & adopt
        return jnp.where(mask[:, None], sent, sg)

    segs = lax.fori_loop(0, total_steps, step, segs)
    return segs.reshape(-1)[:length].reshape(x.shape)


def bcast_scatter_allgather(comm, x, root=0):
    """Scatter + allgather bcast (reference: coll_base_bcast.c knomial/
    scatter_allgather): binomial scatter of chunks then ring allgather —
    bandwidth-optimal for large messages."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    length = x.size
    # scatter: keep only own chunk (root's data is authoritative)
    own = scatter_linear(comm, x, root)
    gathered = allgather_ring(comm, own)
    return gathered.reshape(-1)[:length].reshape(x.shape)


# ---------------------------------------------------------------------------
# Reduce (cf. coll_base_reduce.c)
# ---------------------------------------------------------------------------


def reduce_binomial(comm, x, op, root=0):
    """Binomial-tree reduce (reference: coll_base_reduce.c:471).  Result is
    significant at root (SPMD: other ranks hold partials)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    k = 1
    while k < n:
        pairs = []
        for v in range(0, n - k, 2 * k):
            pairs.append(((v + k + root) % n, (v + root) % n))
        recv = spmd.ppermute(comm, x, pairs)
        is_recv = (vrank % (2 * k) == 0) & (vrank + k < n)
        x = _where(is_recv, op(recv, x), x)
        k <<= 1
    return x


def reduce_linear(comm, x, op, root=0):
    """Linear reduce preserving strict rank order for non-commutative ops."""
    full = allreduce_linear(comm, x, op)
    return full  # every rank computes the rank-ordered result


# ---------------------------------------------------------------------------
# Allgather (cf. coll_base_allgather.c)
# ---------------------------------------------------------------------------


def _stack_shape(x):
    return x[None] if x.ndim == 0 else x


def allgather_ring(comm, x):
    """Ring allgather (reference: coll_base_allgather.c:358)."""
    n = _require_uniform(comm)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice(buf, x[None], (rank,) + (0,) * x.ndim)

    def ag_round(k, b):
        send_idx = (rank - k) % n
        recv_idx = (rank - k - 1) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(sent)

    buf = lax.fori_loop(0, n - 1, ag_round, buf)
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def allgather_bruck(comm, x):
    """Bruck allgather (reference: coll_base_allgather.c:85): ceil(log2 p)
    rounds of doubling block counts, then a rotation."""
    n = _require_uniform(comm)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = buf.at[0].set(x)
    k = 1
    while k < n:
        cnt = min(k, n - k)
        send = buf[:cnt]  # static slice
        recv = spmd.ppermute(
            comm, send, lambda m, k=k: [(i, (i - k) % m) for i in range(m)]
        )
        buf = lax.dynamic_update_slice(
            buf, recv, (k,) + (0,) * (buf.ndim - 1)
        )
        k <<= 1
    # buf[j] holds the block of comm rank (rank + j) % n; rotate to rank order
    buf = jnp.roll(buf, shift=rank, axis=0)
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def allgather_recursive_doubling(comm, x):
    """Recursive-doubling allgather (pow2; reference pattern of
    coll_base_allgather.c). Falls back to Bruck otherwise."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return allgather_bruck(comm, x)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice(buf, x[None], (rank,) + (0,) * x.ndim)
    w = 1
    while w < n:
        pairs = [(i, i ^ w) for i in range(n)]
        my_lo = rank & ~(w - 1)
        partner_lo = (rank ^ w) & ~(w - 1)
        sent = spmd.ppermute(
            comm,
            lax.dynamic_slice(
                buf, (my_lo,) + (0,) * x.ndim, (w,) + x.shape
            ),
            pairs,
        )
        buf = lax.dynamic_update_slice(
            buf, sent, (partner_lo,) + (0,) * x.ndim
        )
        w <<= 1
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


# ---------------------------------------------------------------------------
# Alltoall (cf. coll_base_alltoall.c)
# ---------------------------------------------------------------------------


def _atoall_blocks(comm, x):
    n = _require_uniform(comm)
    if x.shape[0] % n:
        raise errors.CountError(
            f"alltoall needs dim0 divisible by comm size {n}, got {x.shape[0]}"
        )
    m = x.shape[0] // n
    return n, x.reshape((n, m) + x.shape[1:])


def alltoall_pairwise(comm, x):
    """Pairwise exchange (reference: coll_base_alltoall.c:132): p-1 rounds,
    round r exchanges with rank±r."""
    n, blocks = _atoall_blocks(comm, x)
    if n == 1:
        return x
    rank = comm.rank()
    out = jnp.zeros_like(blocks)
    out = out.at[rank].set(jnp.take(blocks, rank, axis=0))

    def round_r(r, o):
        sendto = (rank + r) % n
        recvfrom = (rank - r) % n
        sent = spmd.ppermute(
            comm, jnp.take(blocks, sendto, axis=0),
            lambda m, r=r: [(i, (i + r) % m) for i in range(m)],
        )
        return o.at[recvfrom].set(sent)

    # r is traced inside fori_loop but the ppermute pattern depends on it,
    # so unroll the (static-count) rounds instead.
    for r in range(1, n):
        out = round_r(r, out)
    return out.reshape(x.shape)


def alltoall_bruck(comm, x):
    """Bruck alltoall (reference: coll_base_alltoall.c:191): log2(p) rounds
    moving blocks whose index has bit k set; saves latency for small
    messages at the cost of local rotations."""
    n, blocks = _atoall_blocks(comm, x)
    if n == 1:
        return x
    rank = comm.rank()
    # phase 1: local rotation so block j targets rank (rank + j) % n
    blocks = jnp.roll(blocks, shift=-rank, axis=0)
    # phase 2: for each bit k, send blocks with bit k set to rank + 2^k
    k = 1
    while k < n:
        mask = (jnp.arange(n) & k) != 0
        sent = spmd.ppermute(
            comm, blocks, lambda m, k=k: [(i, (i + k) % m) for i in range(m)]
        )
        blocks = jnp.where(
            mask.reshape((n,) + (1,) * (blocks.ndim - 1)), sent, blocks
        )
        k <<= 1
    # phase 3: after phase 2, slot j at rank d holds data from source
    # (d - j) mod n; restoring source order is a flip then rotate by rank+1
    blocks = jnp.roll(jnp.flip(blocks, axis=0), shift=rank + 1, axis=0)
    return blocks.reshape(x.shape)


# ---------------------------------------------------------------------------
# Reduce_scatter (cf. coll_base_reduce_scatter.c)
# ---------------------------------------------------------------------------


def reduce_scatter_ring(comm, x, op):
    """Ring reduce-scatter (reference: coll_base_reduce_scatter.c:456)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    n_, blocks = _atoall_blocks(comm, x)

    def rs_round(k, b):
        send_idx = (rank - k) % n
        recv_idx = (rank - k - 1) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(op(sent, jnp.take(b, recv_idx, axis=0)))

    blocks = lax.fori_loop(0, n - 1, rs_round, blocks)
    # rank owns chunk (rank+1)%n; shift it home so rank r holds chunk r
    owned = jnp.take(blocks, (rank + 1) % n, axis=0)
    return spmd.shift(comm, owned, 1, wrap=True)


def reduce_scatter_recursive_halving(comm, x, op):
    """Recursive halving (reference: coll_base_reduce_scatter.c:132); pow2
    ranks, falls back to ring otherwise."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return reduce_scatter_ring(comm, x, op)
    if n == 1:
        return x
    rank = comm.rank()
    _, blocks = _atoall_blocks(comm, x)
    shape_rest = blocks.shape[1:]
    lo = jnp.zeros((), jnp.int32)
    bit = n >> 1
    while bit:
        pairs = [(i, i ^ bit) for i in range(n)]
        on_upper = (rank & bit) != 0
        send_lo = jnp.where(on_upper, lo, lo + bit)
        keep_lo = jnp.where(on_upper, lo + bit, lo)
        sent = spmd.ppermute(
            comm,
            lax.dynamic_slice(
                blocks, (send_lo,) + (0,) * len(shape_rest), (bit,) + shape_rest
            ),
            pairs,
        )
        kept = lax.dynamic_slice(
            blocks, (keep_lo,) + (0,) * len(shape_rest), (bit,) + shape_rest
        )
        blocks = lax.dynamic_update_slice(
            blocks, op(sent, kept), (keep_lo,) + (0,) * len(shape_rest)
        )
        lo = keep_lo
        bit >>= 1
    return jnp.take(blocks, rank, axis=0)


# ---------------------------------------------------------------------------
# Scan / Exscan (cf. coll_base_scan.c, coll_base_exscan.c)
# ---------------------------------------------------------------------------


def scan_recursive_doubling(comm, x, op):
    """Inclusive prefix reduction, Hillis-Steele over ranks (reference:
    coll_base_scan.c:157).  Order-preserving: safe for non-commutative
    (associative) ops."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    k = 1
    while k < n:
        recv = spmd.ppermute(
            comm, x, [(i, i + k) for i in range(n - k)]
        )
        x = _where(rank >= k, op(recv, x), x)
        k <<= 1
    return x


def exscan_recursive_doubling(comm, x, op):
    """Exclusive scan (reference: coll_base_exscan.c:142): inclusive scan,
    then shift the RESULTS up one rank — correct for every associative op
    (shifting inputs instead would inject ppermute's zero-fill at rank 0
    into every prefix, which is only an identity for SUM).  Rank 0's result
    is undefined per MPI; here it holds zeros."""
    _require_uniform(comm)
    inclusive = scan_recursive_doubling(comm, x, op)
    return spmd.shift(comm, inclusive, 1, wrap=False)


# ---------------------------------------------------------------------------
# Barrier (cf. coll_base_barrier.c)
# ---------------------------------------------------------------------------


def barrier_dissemination(comm, token=None):
    """Bruck/dissemination barrier (reference: coll_base_barrier.c:253):
    ceil(log2 p) rounds of shifted notifications.  Returns a data-dependent
    zero scalar usable as a sequencing token."""
    n = _require_uniform(comm)
    t = jnp.zeros((), jnp.int32) if token is None else jnp.sum(token).astype(
        jnp.int32
    ) * 0
    k = 1
    while k < n:
        t = t + spmd.ppermute(
            comm, t, lambda m, k=k: [(i, (i + k) % m) for i in range(m)]
        )
        k <<= 1
    return t


# ---------------------------------------------------------------------------
# Gather / Scatter (cf. coll_base_gather.c / coll_base_scatter.c)
# ---------------------------------------------------------------------------


def gather_ring(comm, x, root=0):
    """Gather via allgather.  SPMD note (documented semantic): on a
    single-program machine every device executes the same collective, so the
    "only root receives" optimization of the reference's binomial gather
    (coll_base_gather.c:41) buys nothing — the result is simply significant
    at root."""
    return allgather_ring(comm, x)


def scatter_linear(comm, x, root=0):
    """Linear scatter (reference: coll_base_scatter.c:63): root sends chunk i
    to rank i, one static ppermute per destination; XLA overlaps them."""
    n = _require_uniform(comm)
    buf, length = _chunked(x, n)
    chunk = buf.shape[1]
    rank = comm.rank()
    out = jnp.take(buf, rank, axis=0)  # root's own chunk (and garbage elsewhere)
    for i in range(n):
        if i == root:
            continue
        sent = spmd.ppermute(comm, buf[i], [(root, i)])
        out = _where(rank == i, sent, out)
    # non-root ranks' x may be garbage; out at rank i is root's chunk i
    return out


def bcast_via_scatter(comm, x, root=0):
    return bcast_scatter_allgather(comm, x, root)


# ---------------------------------------------------------------------------
# Vector (v) variants
# ---------------------------------------------------------------------------


def allgatherv_concat(comm, x, counts: list[int]):
    """Allgatherv with static per-rank counts (cf. coll_base_allgatherv.c):
    pad to the max count, exchange, then statically re-concatenate.  `x` is
    this device's contribution, whose dim0 may be any value up to
    max(counts); entries beyond the device's count are ignored."""
    n = _require_uniform(comm)
    if len(counts) != n:
        raise errors.ArgError(f"need {n} counts, got {len(counts)}")
    mx = max(counts)
    pad = mx - x.shape[0]
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    gathered = allgather_ring(comm, x).reshape((n, mx) + x.shape[1:])
    parts = [gathered[i, : counts[i]] for i in range(n)]
    return jnp.concatenate(parts, axis=0)
