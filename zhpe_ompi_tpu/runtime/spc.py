"""Software performance counters (SPC).

Re-design of ``ompi/runtime/ompi_spc.c`` (SURVEY.md §5): named monotonic
counters recorded at API call sites, surfaced through the MPI_T-style
introspection (zmpi-info) and resettable for tests/benchmarks.

Semantics note for a traced runtime: counters record **host-side events** —
under ``jit`` a collective is counted when traced (compiled), not per device
execution.  Eager calls count per call.  This is the honest analog on a
compile-once machine and is documented at the CLI.

Wire-plane counters (recorded by ``pt2pt/tcp.py``):

- ``tcp_bytes_sent`` / ``tcp_bytes_recvd`` — ACTUAL on-wire bytes: every
  length-framed message including its 4-byte header — eager frames,
  rendezvous RTS/CTS/data, FT heartbeats/notices, modex and hello frames.
  (Loopback rank-to-self delivery never hits the wire and is NOT counted.)
- ``tcp_zero_copy_sends`` — sends whose array/bytes payload left as
  out-of-band segments (``dss.pack_frames`` + vectored ``sendmsg``, with
  a zero-copy ``recv_into``/``unpack_from`` receive).  Eager sends copy
  nothing; rendezvous sends park ONE defensive copy (buffer-reuse
  contract) but skip the serialize/reassemble/receive copies.
- ``tcp_copy_bytes_avoided`` — payload bytes that skipped the pack-side
  serialization copy (OOB segment bytes, plus loopback payload bytes).
- ``tcp_loopback_fast_deliveries`` — rank-to-self sends delivered by the
  single-defensive-copy shortcut instead of a full DSS round trip.
- ``tcp_rndv_sends`` — rendezvous (RTS/CTS) transfers initiated.

Nonblocking-engine counters (the deferred-contract isend path,
recorded by ``pt2pt/tcp.py``):

- ``tcp_isend_deferred`` — isends that entered the deferred-contract
  progress engine (eager frames queued for the push-pool workers,
  rendezvous descriptors parked without the copy, sm fragment
  pipelines / full-ring producer continuations).  Born-complete isends
  (loopback, an sm single-slot copy-in that landed immediately) are
  not deferred and not counted.
- ``rndv_park_bytes_avoided`` — payload bytes a rendezvous ISEND
  parked as the caller's own pinned buffers instead of the blocking
  path's defensive ``bytes()`` copy (the writev-style rendezvous: the
  CTS-released push ships the caller's buffers directly).  The OSU
  ``--overlap`` ladder gates on this rising at rendezvous sizes.
- ``tcp_rndv_park_copy_bytes`` — payload bytes the BLOCKING send path
  copied at park time (its buffer-reuse contract holds at return).
  The overlap ladder asserts this stays flat across the isend rungs:
  a silent fallback from the deferred contract to the copy path fails
  CI, it does not hide as a perf regression.

Shared-memory-plane counters (recorded at the per-peer transport
dispatch seam in ``pt2pt/tcp.py``; the rings live in ``pt2pt/sm.py``):

- ``sm_bytes_sent`` / ``sm_bytes_recvd`` — ACTUAL on-ring bytes: every
  fragment's payload plus its 16-byte slot header.  ``recvd`` counts at
  consume time, so a frame parked in a dead peer's ring is visible as a
  sent/recvd imbalance.
- ``sm_eager_sends`` — messages that fit one ring slot (DSS header
  packed straight into slot memory via ``dss.pack_frames_into``; one
  sender-side copy total).
- ``sm_frag_sends`` — messages that took the multi-slot fragment
  pipeline (``sm_max_frag`` per slot; the consumer frees slots while
  the producer still copies).
- ``sm_ring_full_spins`` — producer spins on a full ring (backpressure:
  the in-flight bound the ring capacity enforces); a high rate means
  ``sm_ring_bytes`` is undersized for the traffic.
- ``sm_fallback_tcp_sends`` — data sends to a peer that ADVERTISED a
  shared-memory endpoint we could not ride (boot-id mismatch or an
  unmappable segment): visible degradation, asserted zero along the
  OSU ``--plane sm`` ladder.  Intentional TCP (``sm=0``, remote hosts,
  C ranks, rejoiners) is not counted.
- ``sm_rings_materialized`` — rings demand-mapped into existence by a
  sender's first-contact allocation request (the segment directory
  handshake).  Under han traffic this tracks the role-based bound
  (``domain_size + is_leader × n_groups`` per proc), NOT the universe
  size — the OSU ``--plane numa`` footprint gate reads the per-segment
  allocation bitmap directly.

Matching-engine counters (``pt2pt/matching.py``; the hash-binned
queue walks):

- ``match_comparisons`` — posted/unexpected entry inspections performed
  while matching (the bin walks' actual work).  The binned engine's
  delta on a wildcard-heavy posted/unexpected mix is gated in
  ``tests/test_pt2pt.py`` — a regression to linear scanning shows up
  as a counter explosion, not a mystery slowdown.
- ``match_unexpected_max_depth`` — WATERMARK: the deepest the
  unexpected backlog ever got (recorded at insert on both engines).
  A consumer that stops posting — or a matching bug that strands
  arrivals — is visible here even after the queues drain.

Hierarchical-collective counters (the coll/han analog; recorded by
``coll/han.py`` and the ``pt2pt/groups.py`` GroupView send seam):

- ``coll_han_leader_elections`` — locality-group structures built (the
  deterministic min-rank leader election that accompanies each new
  group layout on an endpoint: first engagement, post-shrink rebuild,
  post-JOIN re-derivation).
- ``coll_han_intra_bytes`` — payload bytes sent by intra-phase
  (same-host group) traffic; rides the sm rings through the send seam.
- ``coll_han_inter_bytes`` — payload bytes sent by inter-phase
  (leader-to-leader) traffic — the bytes that actually cross the wire;
  the OSU ``--plane han`` ladder asserts this rises on a multi-group
  topology AND stays strictly below the flat ring's wire bytes at
  equal payload.
- ``han_flat_fallbacks`` — collectives that REQUESTED the hierarchical
  path (``coll_han_enable=on`` or a ``han`` dynamic-rules line) but ran
  flat (degenerate topology, non-commutative op): loud degradation,
  asserted zero along the OSU han ladder's 2-host × 2-rank topology.
  The ``auto`` mode's decision not to engage is not a fallback and is
  not counted.
- ``coll_han_pipelined`` — allreduces whose segmented leader exchange
  took the PIPELINED schedule (``coll_han_pipeline`` auto/on, >= 2
  segments): segment k's intra bcast isends drain on the deferred
  engine while segment k+1's wire exchange runs.  The OSU ``--plane
  han`` pipeline row gates on this rising at >= 2-segment sizes.
- ``coll_han_numa_collectives`` — collectives that ran the THREE-level
  (NUMA) schedule (``coll_han_numa_level`` auto/on on a nested
  topology): intra-domain phase → intra-host domain-leader exchange →
  inter-host wire exchange.  The OSU ``--plane numa`` ladder gates on
  this rising.
- ``coll_han_dleader_bytes`` — payload bytes of the three-level
  schedule's intra-host domain-leader exchange (same-host sm traffic,
  accounted apart from both the domain phase and the wire phase; the
  bytes a domains-as-hosts layout would have paid at wire prices).
- ``han_numa_fallbacks`` — collectives that REQUESTED the three-level
  schedule (``coll_han_numa_level=on``) but ran TWO-level because the
  NUMA structure is degenerate: loud degradation — never silent, and
  never all the way to flat while the host level is viable (the
  two-level fallback contract).  ``auto`` declining to nest is not a
  fallback and is not counted.
- ``han_malformed_numa_cards`` — ranks whose ``pynuma:`` card item was
  present but unusable during topology derivation: counted and demoted
  to a singleton domain (a malformed FOREIGN card must never raise out
  of a collective).

Runtime-plane counters (the PRRTE/PMIx analog — ``runtime/pmix.py``
records the ``pmix_*`` family in the process hosting the STORE, i.e.
the daemon; ``runtime/dvm.py`` records the daemon-side ``dvm_*`` events
and ``pt2pt/tcp.py`` records ``dvm_fault_events`` again in each
SURVIVOR that ingests the frame — the daemon's ``stat`` RPC surfaces
the daemon-side values):

- ``pmix_puts`` / ``pmix_gets`` / ``pmix_fences`` — PMIx verb traffic
  against the name-served KV store: staged puts, blocking
  get-until-published reads (one per published key read, not per
  wait wakeup), and completed fence ENTRIES (one per rank released,
  not per barrier).  A cold 4-rank modex is 4 puts + 4 fence entries
  + 16 gets; the OSU ``--launch`` ladder gates on these moving only
  on the DVM rows.
- ``dvm_jobs_launched`` — jobs spawned into the resident VM (one per
  ``launch`` RPC that reached the spawn loop).
- ``dvm_fault_events`` — authoritative daemon fault events: in the
  daemon, one per child whose ``waitpid`` returned nonzero in an ft
  job; in a survivor, one per NEWLY-learned corpse an ``FT_DVM_CID``
  frame delivered (cause ``"daemon"`` — OS truth, never a detector
  false positive).
- ``dvm_respawns`` — replacement processes exec'd by the relaunch RPC
  (N victims respawned in one batched RPC count N, but share ONE
  namespace-generation bump — the same recovery window).

API-surface counters (recorded at the MPI/OpenSHMEM call sites; the
ZL006 doc-parity rule keeps this table and the ``spc.record`` call
sites in lockstep):

- ``init_count`` — runtime initializations (``runtime/init.py``: both
  the in-process ``init()`` and the ``host_init`` coordinator-contract
  path).
- ``pt2pt_sends`` / ``pt2pt_bytes_sent`` — thread-plane
  (``RankContext``) isends and their payload bytes; the wire plane's
  twin is the ``tcp_*``/``sm_*`` family.
- ``osc_puts`` / ``osc_gets`` / ``osc_bytes_put`` — one-sided window
  operations (both the passive ``window.py`` plane and the
  active-message ``osc/am.py`` plane record the same names: the
  counter tracks the OP, not the transport).
- ``osc_am_applied`` — active-message operations applied at the
  TARGET by the AM service dispatch (origin-side ops count in
  ``osc_puts``/``osc_gets``).
- ``shmem_puts`` / ``shmem_gets`` / ``shmem_puts_nbi`` / ``shmem_gets_nbi``
  — OpenSHMEM put/get traffic, blocking and nonblocking-implicit.
- ``pgas_device_epochs`` — device-heap epoch advances (the PGAS
  quiet/fence boundary on the device plane).
- ``io_nonblocking_ops`` — nonblocking file operations submitted to
  the fbtl async pool.
"""

from __future__ import annotations

import threading
from collections import defaultdict

_counters: dict[str, int] = defaultdict(int)
_lock = threading.Lock()

WATERMARK = {"max_bytes_in_collective", "match_unexpected_max_depth"}


def record(name: str, value: int = 1) -> None:
    with _lock:
        if name in WATERMARK:
            _counters[name] = max(_counters[name], value)
        else:
            _counters[name] += value


def read(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def snapshot() -> dict[str, int]:
    with _lock:
        return dict(_counters)


def reset() -> None:
    with _lock:
        _counters.clear()
