"""Symmetric heap allocator (reference: ``oshmem/mca/memheap``).

The reference offers buddy and ptmalloc components carving a pre-created
shared segment (``sshmem/{mmap,sysv}``).  What makes a heap *symmetric* is
not the allocator policy but determinism: every PE performs the same
allocation sequence, so identical offsets come out — remote addresses are
computed, never exchanged.  This first-fit free-list allocator is fully
deterministic, coalesces on free, and aligns to 64 bytes (the reference
aligns to cache lines; TPU HBM tiles like wider alignment too).

The 64-byte floor is also what the direct-map one-sided plane leans on
(``osc/direct.py`` region-backed heaps): every element of every
allocation is NATURALLY aligned for its dtype, so typed AMOs against
the mapped region can never straddle an atomicity boundary.  The
``align`` parameter is the ``shmem_align`` contract — callers may raise
(never lower) the alignment, e.g. to page-align a buffer they intend to
hand to the device plane; determinism is preserved because the request
sequence, including alignments, is identical on every PE.
"""

from __future__ import annotations

from ..core import errors

ALIGN = 64


class SymmetricHeapAllocator:
    """First-fit free-list over a fixed-size arena of bytes."""

    def __init__(self, size: int):
        if size <= 0:
            raise errors.ArgError("heap size must be positive")
        self.size = size
        # sorted list of (offset, length) free extents
        self._free: list[tuple[int, int]] = [(0, size)]
        self._live: dict[int, int] = {}  # offset -> allocated length

    def alloc(self, nbytes: int, align: int = ALIGN) -> int:
        """Return the offset of a new block; raises when the arena is
        exhausted (the reference's memheap grows via mmap; a fixed arena
        keeps offsets stable, which symmetric addressing needs).
        ``align`` (shmem_align) must be a power of two; the 64-byte
        floor always applies, and alignment padding stays on the free
        list (no hidden per-allocation loss)."""
        if nbytes <= 0:
            raise errors.ArgError("alloc size must be positive")
        align = max(int(align), ALIGN)
        if align & (align - 1):
            raise errors.ArgError(
                f"alignment {align} is not a power of two"
            )
        want = -(-nbytes // ALIGN) * ALIGN
        for i, (off, length) in enumerate(self._free):
            aoff = -(-off // align) * align
            pad = aoff - off
            if length >= pad + want:
                pieces = []
                if pad:
                    pieces.append((off, pad))
                rest = length - pad - want
                if rest:
                    pieces.append((aoff + want, rest))
                self._free[i:i + 1] = pieces
                self._live[aoff] = want
                return aoff
        raise errors.ResourceError(
            f"symmetric heap exhausted: want {want} bytes"
        )

    def free(self, offset: int) -> None:
        length = self._live.pop(offset, None)
        if length is None:
            raise errors.ArgError(f"free of unallocated offset {offset}")
        self._free.append((offset, length))
        self._free.sort()
        # coalesce adjacent extents
        merged: list[tuple[int, int]] = []
        for off, ln in self._free:
            if merged and merged[-1][0] + merged[-1][1] == off:
                merged[-1] = (merged[-1][0], merged[-1][1] + ln)
            else:
                merged.append((off, ln))
        self._free = merged

    @property
    def live_bytes(self) -> int:
        return sum(self._live.values())
