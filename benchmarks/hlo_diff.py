"""VERDICT round-2 item 7: explain vs_baseline > 1.

Dumps the optimized HLO of the framework train step and the plain-JAX
baseline step (exactly as bench.py builds them) and reports whether they
differ.  Identical HLO => any persistent timing delta is measurement
noise and vs_baseline should read ~1.0.

Run: python benchmarks/hlo_diff.py  (CPU or TPU; module structure only)
"""

import difflib
import re
import sys

import numpy as np


def canon(text: str) -> str:
    """Canonicalize HLO text: strip metadata/ids that differ between two
    otherwise-identical programs."""
    out = []
    for line in text.splitlines():
        stripped = line.strip()
        # source-location tables (stack frame indexes): pure metadata
        if re.match(r'^\d+ (\{[^}]*\}|")', stripped):
            continue
        line = re.sub(r"metadata=\{[^}]*\}", "", line)
        line = re.sub(r'"[^"]*"', '""', line)
        # computation/instruction numbering suffixes (.NN) differ freely
        line = re.sub(r"\.\d+", "", line)
        # argument names differ between the two harness functions
        # (params/tokens/targets vs p/tok/tgt) — not part of the program
        line = re.sub(r"params__(\w+?)__", r"p__\1__", line)
        line = line.replace("%tokens", "%tok").replace("%targets", "%tgt")
        line = line.replace("tokens:", "tok:").replace("targets:", "tgt:")
        out.append(line.rstrip())
    return "\n".join(out)


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu import compat
    from zhpe_ompi_tpu.models import transformer as tfm

    devs = jax.devices()
    n = len(devs)
    tp = 2 if n % 2 == 0 else 1
    dp = n // tp
    mesh = Mesh(np.asarray(devs[: dp * tp]).reshape(dp, tp), ("dp", "tp"))
    dp_comm = zmpi.Communicator(mesh, "dp", name="hlo_dp")
    tp_comm = zmpi.Communicator(mesh, "tp", name="hlo_tp") if tp > 1 else None

    on_tpu = devs[0].platform not in ("cpu",)
    if on_tpu:
        cfg = tfm.Config(vocab=8192, d_model=1024, n_heads=16, d_ff=4096,
                         n_layers=4, seq=512, dtype=jnp.bfloat16)
        batch = 8 * dp
    else:
        cfg = tfm.Config(vocab=256, d_model=128, n_heads=8, d_ff=512,
                         n_layers=2, seq=128, dtype=jnp.float32)
        batch = 2 * dp

    r = np.random.default_rng(0)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
    targets = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))

    step_fw, specs = tfm.make_train_step(cfg, mesh, dp_comm, tp_comm)

    # rebuild the plain step exactly as bench.py does
    from jax import lax

    class RawComm:
        def __init__(self, axis):
            self.axis = axis

        def allreduce(self, x, op):
            return lax.psum(x, self.axis)

    raw_tp = RawComm("tp") if tp > 1 else None

    def spmd_step(p, tok, tgt):
        def local_loss(pp):
            return tfm.loss_fn(pp, tok, tgt, cfg, raw_tp)

        loss, grads = jax.value_and_grad(local_loss)(p)
        synced = {}
        replicated = {"embed", "lnf", "ln1", "ln2"}
        for name, g in grads.items():
            g = lax.psum(g, "dp") / dp
            if name in replicated and raw_tp is not None:
                g = lax.psum(g, "tp") / tp
            synced[name] = g
        loss = lax.psum(loss, "dp") / dp
        if raw_tp is not None:
            loss = lax.psum(loss, "tp") / tp
        new_p = jax.tree.map(
            lambda a, g: (a - 1e-2 * g).astype(a.dtype), p, synced
        )
        return new_p, loss

    step_pl = jax.jit(compat.shard_map(
        spmd_step, mesh=mesh,
        in_specs=(specs, P("dp"), P("dp")),
        out_specs=(specs, P()), check_vma=False,
    ))

    sharded = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
               for k, v in params.items()}
    dspec = NamedSharding(mesh, P("dp"))
    tok = jax.device_put(tokens, dspec)
    tgt = jax.device_put(targets, dspec)

    hlo_fw = canon(
        step_fw.lower(sharded, tok, tgt).compile()
        .as_text())
    hlo_pl = canon(
        step_pl.lower(sharded, tok, tgt).compile()
        .as_text())

    if hlo_fw == hlo_pl:
        print("HLO IDENTICAL: framework and plain paths compile to the "
              "same program; vs_baseline deltas are measurement noise.")
        return 0
    fw_lines, pl_lines = hlo_fw.splitlines(), hlo_pl.splitlines()
    diff = list(difflib.unified_diff(pl_lines, fw_lines,
                                     "plain", "framework", lineterm="", n=0))
    print(f"HLO DIFFERS: {len(diff)} diff lines "
          f"(fw {len(fw_lines)} vs plain {len(pl_lines)} lines)")
    for line in diff[:80]:
        print(line)
    return 1


if __name__ == "__main__":
    sys.exit(main())
