"""OSU-microbenchmark-style harness (SURVEY.md §6).

The reference ships no benchmarks in-tree — Open MPI is measured with the
external OSU/IMB suites (osu_allreduce, osu_bcast, osu_latency).  This is
the in-tree equivalent for the TPU-native framework: per-algorithm
collective latency/bandwidth sweeps over OSU's size ladder, and a
host-plane ping-pong latency test, all emitting the familiar two-column
table.

Usage::

    python -m benchmarks.osu_zmpi --op allreduce --algorithm ring
    python -m benchmarks.osu_zmpi --op bcast --max-size 1048576
    python -m benchmarks.osu_zmpi --op pt2pt
    python -m benchmarks.osu_zmpi --op pt2pt --bw --json   # osu_bw shape
    python -m benchmarks.osu_zmpi --op tcp --bw
    python -m benchmarks.osu_zmpi --op allreduce --plane host --algorithm ring
    python -m benchmarks.osu_zmpi --op all --json

``--bw`` switches the pt2pt/tcp ops from ping-pong latency (osu_latency)
to the multi-frame in-flight bandwidth shape (osu_bw): the sender streams
a window of frames back-to-back, the receiver acks once per window —
measuring the wire plane's streaming throughput, where the zero-copy
framing matters most.  ``--plane host`` runs the collective over REAL
loopback sockets through coll/host (the DCN leg), instead of the
device-plane XLA collectives.

On a CPU host this exercises the 8-virtual-device loopback mesh (the
btl/self+sm analog); on TPU hardware the same sweep rides ICI.
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Callable

import numpy as np


def _sizes(max_bytes: int, min_bytes: int = 4) -> list[int]:
    out = []
    s = min_bytes
    while s <= max_bytes:
        out.append(s)
        s *= 4
    return out


def _time_op(fn: Callable[[], None], warmup: int = 2, iters: int = 10
             ) -> float:
    """Median wall-clock seconds of fn() (fn must block to completion)."""
    for _ in range(warmup):
        fn()
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        samples.append(time.perf_counter() - t0)
    return float(np.median(samples))


def bench_collective(opname: str, algorithm: str = "auto",
                     max_size: int = 4 << 20, iters: int = 10,
                     dtype=None) -> list[dict]:
    """Latency sweep of one collective, optionally pinning the tuned
    algorithm (the MCA forced-algorithm knob)."""
    import jax
    import jax.numpy as jnp

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu.mca import var as mca_var

    world = zmpi.init()
    n = world.size
    dtype = dtype or jnp.float32
    itemsize = jnp.dtype(dtype).itemsize

    rows = []
    for nbytes in _sizes(max_size):
        count = max(n, nbytes // itemsize)
        count = -(-count // n) * n  # divisible by n for scatter-type ops
        x = jnp.arange(n * count, dtype=dtype).reshape(n, count)
        xs = world.device_put_sharded(x)

        if algorithm != "auto":
            mca_var.set_var(f"coll_tuned_{opname}_algorithm", algorithm)
        try:
            if opname in ("allreduce", "reduce", "reduce_scatter",
                          "reduce_scatter_block", "scan", "exscan"):
                per_dev = lambda s: getattr(world, opname)(s.reshape(count))
            elif opname in ("bcast", "gather", "scatter"):
                per_dev = lambda s: getattr(world, opname)(
                    s.reshape(count), 0
                )
            else:  # allgather, alltoall, barrier
                per_dev = lambda s: getattr(world, opname)(s.reshape(count))
            jitted = jax.jit(
                lambda a: world.run(per_dev, a)
            )
            out = jitted(xs)  # compile
            jax.block_until_ready(out)
            sec = _time_op(
                lambda: jax.block_until_ready(jitted(xs)), iters=iters
            )
        finally:
            if algorithm != "auto":
                mca_var.set_var(f"coll_tuned_{opname}_algorithm", "auto")

        rows.append({
            "op": opname, "algorithm": algorithm, "bytes": count * itemsize,
            "latency_us": sec * 1e6,
            "bandwidth_MBps": (count * itemsize / sec) / 1e6,
        })
    return rows


def bench_pt2pt(max_size: int = 4 << 20, iters: int = 50,
                bw: bool = False, window: int = 16) -> list[dict]:
    """Host-plane pt2pt over the thread-rank universe — the btl/self+sm
    loopback analog.  Default: ping-pong latency (osu_latency shape).
    ``bw=True``: multi-frame in-flight bandwidth (osu_bw shape — the
    sender streams `window` messages, the receiver acks per window)."""
    from zhpe_ompi_tpu.pt2pt.requests import wait_all
    from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse

    rows = []
    for nbytes in _sizes(max_size):
        payload = np.zeros(max(1, nbytes // 8), dtype=np.float64)
        uni = LocalUniverse(2)

        def main_latency(ctx, payload=payload):
            if ctx.rank == 0:
                # warmup
                ctx.send(payload, dest=1, tag=1)
                ctx.recv(source=1, tag=2)
                t0 = time.perf_counter()
                for _ in range(iters):
                    ctx.send(payload, dest=1, tag=1)
                    ctx.recv(source=1, tag=2)
                return (time.perf_counter() - t0) / iters
            ctx.recv(source=0, tag=1)
            ctx.send(payload, dest=0, tag=2)
            for _ in range(iters):
                ctx.recv(source=0, tag=1)
                ctx.send(payload, dest=0, tag=2)
            return None

        def main_bw(ctx, payload=payload):
            reps = max(1, iters // 4)
            if ctx.rank == 0:
                wait_all([ctx.isend(payload, 1, tag=1)
                          for _ in range(window)])
                ctx.recv(source=1, tag=2)  # warmup window + ack
                t0 = time.perf_counter()
                for _ in range(reps):
                    wait_all([ctx.isend(payload, 1, tag=1)
                              for _ in range(window)])
                    ctx.recv(source=1, tag=2)
                # seconds per one-way message, amortized over the window
                return (time.perf_counter() - t0) / (reps * window)
            for _ in range(reps + 1):
                reqs = [ctx.irecv(source=0, tag=1) for _ in range(window)]
                wait_all(reqs)
                ctx.send(b"ack", dest=0, tag=2)
            return None

        sec = uni.run(main_bw if bw else main_latency)[0]
        one_way = sec if bw else sec / 2
        rows.append({
            "op": "pt2pt_bw" if bw else "pt2pt_pingpong",
            "bytes": payload.nbytes,
            "latency_us": one_way * 1e6,  # one-way, OSU convention
            "bandwidth_MBps": (payload.nbytes / one_way) / 1e6,
        })
    return rows


def _run_tcp_ranks(n: int, fn, timeout: float = 180.0) -> list:
    """Launch fn(proc) on n TcpProc ranks over localhost sockets; rank 0
    binds an ephemeral coordinator the others learn through the
    on_coordinator_bound hook (prte forwarding the PMIx URI)."""
    import threading

    from zhpe_ompi_tpu.pt2pt.tcp import TcpProc

    coord: list = []
    coord_ready = threading.Event()
    results: list = [None] * n
    excs: list = [None] * n

    def main(rank):
        try:
            if rank == 0:
                proc = TcpProc(
                    0, n, coordinator=("127.0.0.1", 0),
                    on_coordinator_bound=lambda addr: (
                        coord.append(addr), coord_ready.set()),
                )
            else:
                if not coord_ready.wait(30.0) or not coord:
                    return  # rank 0 failed; its error is in excs[0]
                proc = TcpProc(rank, n, coordinator=tuple(coord[0]))
            try:
                results[rank] = fn(proc)
            finally:
                proc.close()
        except BaseException as e:  # noqa: BLE001 - re-raised below
            excs[rank] = e
            coord_ready.set()

    threads = [threading.Thread(target=main, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
    for e in excs:
        if e is not None:
            raise RuntimeError(f"tcp bench rank failed: {e!r}") from e
    return results


def bench_tcp(max_size: int = 4 << 20, iters: int = 50,
              bw: bool = False, window: int = 16) -> list[dict]:
    """REAL-socket pt2pt (over btl/tcp): two TcpProc endpoints over
    loopback, eager and rendezvous regimes both crossed as the ladder
    passes tcp_eager_limit.  Default: ping-pong latency (osu_latency).
    ``bw=True``: multi-frame in-flight bandwidth (osu_bw — `window`
    frames streamed per ack, so TCP keeps its pipe full)."""
    rows = []
    for nbytes in _sizes(max_size):
        payload = np.zeros(max(1, nbytes // 8), dtype=np.float64)

        def pingpong(proc, payload=payload):
            if proc.rank == 0:
                proc.send(payload, dest=1, tag=1)
                proc.recv(source=1, tag=2)
                t0 = time.perf_counter()
                for _ in range(iters):
                    proc.send(payload, dest=1, tag=1)
                    proc.recv(source=1, tag=2)
                return (time.perf_counter() - t0) / iters
            proc.recv(source=0, tag=1)
            proc.send(payload, dest=0, tag=2)
            for _ in range(iters):
                proc.recv(source=0, tag=1)
                proc.send(payload, dest=0, tag=2)
            return None

        def stream(proc, payload=payload):
            reps = max(1, iters // 4)
            if proc.rank == 0:
                for _ in range(window):
                    proc.send(payload, dest=1, tag=1)
                proc.recv(source=1, tag=2)  # warmup window + ack
                t0 = time.perf_counter()
                for _ in range(reps):
                    for _ in range(window):
                        proc.send(payload, dest=1, tag=1)
                    proc.recv(source=1, tag=2)
                return (time.perf_counter() - t0) / (reps * window)
            for _ in range(reps + 1):
                for _ in range(window):
                    proc.recv(source=0, tag=1, timeout=120.0)
                proc.send(b"ack", dest=0, tag=2)
            return None

        sec = _run_tcp_ranks(2, stream if bw else pingpong)[0]
        one_way = sec if bw else sec / 2
        rows.append({
            "op": "tcp_bw" if bw else "tcp_pingpong",
            "bytes": payload.nbytes,
            "latency_us": one_way * 1e6,
            "bandwidth_MBps": (payload.nbytes / one_way) / 1e6,
        })
    return rows


def bench_host_coll(opname: str = "allreduce", algorithm: str = "auto",
                    max_size: int = 4 << 20, iters: int = 5,
                    nprocs: int = 4) -> list[dict]:
    """Host-plane collective over REAL loopback sockets: `nprocs`
    TcpProc ranks running the coll/host algorithms (ring allreduce,
    pipeline bcast, pairwise alltoall ... the DCN leg of multi-host
    training).  ``algorithm`` pins the host algorithm MCA var where one
    exists; 'ring' for allreduce means crossing host_coll_large_msg so
    the bandwidth-optimal ring path is selected."""
    from zhpe_ompi_tpu import ops
    from zhpe_ompi_tpu.mca import var as mca_var

    pinned = None
    if algorithm != "auto" and opname in ("bcast", "reduce"):
        pinned = f"host_{opname}_algorithm"
        mca_var.set_var(pinned, algorithm)
    elif algorithm == "ring" and opname == "allreduce":
        # the ring path has no forced-algorithm var; it is selected by
        # size — drop the threshold so EVERY rung actually runs ring
        # and the row's algorithm label is honest
        pinned = "host_coll_large_msg"
        mca_var.set_var(pinned, 1)
    elif algorithm != "auto":
        raise ValueError(
            f"host plane: no algorithm knob for {opname}/{algorithm}"
        )
    try:
        rows = []
        for nbytes in _sizes(max_size, min_bytes=1 << 10):
            arr = np.zeros(max(nprocs, nbytes // 8), dtype=np.float64)

            def prog(p, arr=arr):
                def once():
                    if opname == "allreduce":
                        p.allreduce(arr, ops.SUM)
                    elif opname == "bcast":
                        p.bcast(arr if p.rank == 0 else None, 0)
                    elif opname == "alltoall":
                        blocks = np.array_split(arr, p.size)
                        p.alltoall(list(blocks))
                    else:
                        raise ValueError(f"host plane: unknown {opname}")

                once()  # warmup
                p.barrier()
                t0 = time.perf_counter()
                for _ in range(iters):
                    once()
                return (time.perf_counter() - t0) / iters

            per_rank = _run_tcp_ranks(nprocs, prog)
            sec = max(per_rank)
            rows.append({
                "op": f"host_{opname}", "algorithm": algorithm,
                "bytes": arr.nbytes, "latency_us": sec * 1e6,
                "bandwidth_MBps": (arr.nbytes / sec) / 1e6,
            })
        return rows
    finally:
        if pinned:
            mca_var.unset(pinned)


def _print_table(rows: list[dict]) -> None:
    if not rows:
        return
    print(f"# {rows[0]['op']}"
          + (f" [{rows[0]['algorithm']}]" if "algorithm" in rows[0] else ""))
    print(f"{'Size (B)':>12} {'Latency (us)':>16} {'BW (MB/s)':>14}")
    for r in rows:
        print(f"{r['bytes']:>12} {r['latency_us']:>16.2f} "
              f"{r['bandwidth_MBps']:>14.1f}")


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--op", default="allreduce",
                   help="allreduce|bcast|allgather|alltoall|reduce|"
                        "reduce_scatter|pt2pt|tcp|all")
    p.add_argument("--algorithm", default="auto",
                   help="tuned forced algorithm name, or 'auto'")
    p.add_argument("--max-size", type=int, default=1 << 20)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--json", action="store_true")
    p.add_argument("--bw", action="store_true",
                   help="pt2pt/tcp: multi-frame in-flight bandwidth "
                        "(osu_bw shape) instead of ping-pong latency")
    p.add_argument("--window", type=int, default=16,
                   help="frames in flight per ack in --bw mode")
    p.add_argument("--plane", default="device",
                   choices=("device", "host"),
                   help="collectives: device = XLA mesh (default); "
                        "host = coll/host over real loopback sockets")
    p.add_argument("--nprocs", type=int, default=4,
                   help="socket ranks for --plane host")
    args = p.parse_args(argv)

    if args.op == "pt2pt":
        rows = bench_pt2pt(args.max_size, max(args.iters, 10),
                           bw=args.bw, window=args.window)
    elif args.op == "tcp":
        rows = bench_tcp(args.max_size, max(args.iters, 10),
                         bw=args.bw, window=args.window)
    elif args.op == "all":
        rows = []
        for op in ("allreduce", "bcast", "allgather", "alltoall"):
            rows += bench_collective(op, "auto", args.max_size, args.iters)
        rows += bench_pt2pt(args.max_size, max(args.iters, 10))
        rows += bench_tcp(args.max_size, max(args.iters, 10))
    elif args.plane == "host":
        rows = bench_host_coll(
            args.op, args.algorithm, args.max_size, args.iters,
            nprocs=args.nprocs,
        )
    else:
        rows = bench_collective(
            args.op, args.algorithm, args.max_size, args.iters
        )

    if args.json:
        for r in rows:
            print(json.dumps(r))
    else:
        _print_table(rows)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
