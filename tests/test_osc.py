"""One-sided communication tests: host windows + SPMD device windows."""

import numpy as np
import pytest

import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.osc import DeviceWindow, HostWindow
from zhpe_ompi_tpu.pt2pt.universe import LocalUniverse


class TestHostWindow:
    def test_put_get_fence(self):
        uni = LocalUniverse(4)

        def main(ctx):
            buf = np.zeros(8, np.float32)
            win = HostWindow.create(ctx, buf)
            win.fence()
            # everyone puts its rank into slot `rank` of rank 0's window
            win.put(np.float32(ctx.rank + 1), target=0, offset=ctx.rank)
            win.fence()
            if ctx.rank == 0:
                return buf[:4].tolist()
            return None

        assert uni.run(main)[0] == [1.0, 2.0, 3.0, 4.0]

    def test_get(self):
        uni = LocalUniverse(2)

        def main(ctx):
            buf = np.full(4, float(ctx.rank * 10), np.float32)
            win = HostWindow.create(ctx, buf)
            win.fence()
            other = 1 - ctx.rank
            got = win.get(other, offset=0, count=4)
            win.fence()
            return got.tolist()

        res = uni.run(main)
        assert res[0] == [10.0] * 4 and res[1] == [0.0] * 4

    def test_accumulate_atomic(self):
        """Concurrent accumulates from all ranks must not lose updates."""
        uni = LocalUniverse(8)
        iters = 50

        def main(ctx):
            buf = np.zeros(1, np.int64)
            win = HostWindow.create(ctx, buf)
            win.fence()
            for _ in range(iters):
                win.accumulate(np.int64(1), target=0, offset=0)
            win.fence()
            return int(buf[0])

        res = uni.run(main)
        assert res[0] == 8 * iters

    def test_get_accumulate(self):
        uni = LocalUniverse(4)

        def main(ctx):
            buf = np.zeros(1, np.int64)
            win = HostWindow.create(ctx, buf)
            win.fence()
            old = win.get_accumulate(np.int64(1), target=0, offset=0)
            win.fence()
            return int(old[0])

        res = uni.run(main)
        assert sorted(res) == [0, 1, 2, 3]  # each saw a distinct pre-value

    def test_compare_and_swap(self):
        uni = LocalUniverse(4)

        def main(ctx):
            buf = np.zeros(1, np.int64)
            win = HostWindow.create(ctx, buf)
            win.fence()
            old = win.compare_and_swap(ctx.rank + 1, compare=0, target=0)
            win.fence()
            winner = int(buf[0]) if ctx.rank == 0 else None
            return (int(old), winner)

        res = uni.run(main)
        olds = [o for o, _ in res]
        assert olds.count(0) == 1  # exactly one rank won the CAS
        assert res[0][1] in (1, 2, 3, 4)

    def test_lock_unlock(self):
        uni = LocalUniverse(4)

        def main(ctx):
            buf = np.zeros(1, np.float64)
            win = HostWindow.create(ctx, buf)
            win.fence()
            for _ in range(20):
                win.lock(0)
                v = win.get(0, 0, 1)[0]
                win.put(np.float64(v + 1), 0, 0)
                win.unlock(0)
            win.fence()
            return float(buf[0])

        assert uni.run(main)[0] == 80.0

    def test_pscw(self):
        """Real PSCW semantics: wait_sync alone must block until every
        origin's complete() — no auxiliary barrier."""
        uni = LocalUniverse(3)

        def main(ctx):
            buf = np.zeros(4, np.float32)
            win = HostWindow.create(ctx, buf)
            if ctx.rank == 0:
                win.post(origins=[1, 2])
                win.wait_sync()
                return buf[:2].tolist()
            win.start([0])
            win.put(np.float32(ctx.rank), target=0, offset=ctx.rank - 1)
            win.complete()
            return None

        assert uni.run(main)[0] == [1.0, 2.0]

    def test_pscw_two_epochs(self):
        """Back-to-back epochs must not race (epoch counters, not events)."""
        uni = LocalUniverse(2)

        def main(ctx):
            buf = np.zeros(1, np.float32)
            win = HostWindow.create(ctx, buf)
            out = []
            for epoch in range(3):
                if ctx.rank == 0:
                    win.post(origins=[1])
                    win.wait_sync()
                    out.append(float(buf[0]))
                else:
                    win.start([0])
                    win.put(np.float32(epoch + 1), target=0, offset=0)
                    win.complete()
            return out

        assert uni.run(main)[0] == [1.0, 2.0, 3.0]

    def test_noncontiguous_buffer_rejected(self):
        """A strided view would make reshape(-1) a copy and RMA writes
        vanish; create() must refuse it (before any communication, so both
        ranks fail symmetrically with no deadlock)."""
        uni = LocalUniverse(2)

        def main(ctx):
            big = np.zeros(8, np.float32)
            with pytest.raises(errors.WinError):
                HostWindow.create(ctx, big[::2])
            return True

        assert uni.run(main) == [True, True]

    def test_free_releases_registry(self):
        uni = LocalUniverse(2)

        def main(ctx):
            buf = np.zeros(2, np.float32)
            win = HostWindow.create(ctx, buf)
            win.fence()
            key = (id(ctx.universe), win.win_id)
            win.free()
            return key in HostWindow._registries

        assert uni.run(main) == [False, False]

    def test_bounds_checked(self):
        uni = LocalUniverse(2)

        def main(ctx):
            buf = np.zeros(2, np.float32)
            win = HostWindow.create(ctx, buf)
            win.fence()
            err = None
            if ctx.rank == 1:
                try:
                    win.put(np.zeros(8, np.float32), target=0, offset=0)
                except errors.WinError as e:
                    err = str(e)
            win.fence()
            return err

        assert "overruns" in uni.run(main)[1]


class TestDeviceWindow:
    @pytest.fixture(scope="class")
    def world(self):
        return zmpi.init()

    def test_put_ring(self, world):
        """Halo-pattern: every rank puts its value into right neighbor."""
        import jax.numpy as jnp

        n = 8
        x = np.arange(n, dtype=np.float32).reshape(n, 1)
        target_of = [(i + 1) % n for i in range(n)]
        offset_of = [0] * n

        def body(s):
            win = DeviceWindow(world, jnp.zeros(2, jnp.float32))
            win = win.put(s.reshape(1), target_of, offset_of)
            return win.shard.reshape(1, 2)

        out = np.asarray(
            world.run(body, world.device_put_sharded(jnp.asarray(x)))
        ).reshape(n, 2)
        np.testing.assert_allclose(out[:, 0], np.roll(np.arange(n), 1))

    def test_get(self, world):
        import jax.numpy as jnp

        n = 8
        x = (np.arange(n, dtype=np.float32) * 10).reshape(n, 1)
        source_of = [(i + 1) % n for i in range(n)]  # read right neighbor
        offset_of = [0] * n

        def body(s):
            win = DeviceWindow(world, s.reshape(1))
            got = win.get(source_of, offset_of, count=1)
            return got.reshape(1, 1)

        out = np.asarray(
            world.run(body, world.device_put_sharded(jnp.asarray(x)))
        ).reshape(n)
        np.testing.assert_allclose(out, np.roll(np.arange(n) * 10, -1))

    def test_accumulate(self, world):
        import jax.numpy as jnp

        n = 8
        x = np.ones((n, 1), np.float32)
        # ring schedule: every rank accumulates into its right neighbor
        ring = [(i + 1) % n for i in range(n)]

        def body(s):
            win = DeviceWindow(world, jnp.full((1,), 100.0, jnp.float32))
            win = win.accumulate(s.reshape(1), ring, [0] * n)
            return win.shard.reshape(1, 1)

        out = np.asarray(
            world.run(body, world.device_put_sharded(jnp.asarray(x)))
        ).reshape(n)
        np.testing.assert_allclose(out, np.full(n, 101.0))

    def test_passive_target_rejected_with_pointer(self, world):
        """Round-4 (VERDICT weak #6): lock/flush on a device window must
        fail loudly naming the AM component, not AttributeError."""
        import jax.numpy as jnp

        from zhpe_ompi_tpu.core import errors

        win = DeviceWindow(world, jnp.zeros(2, jnp.float32))
        for meth in ("lock", "lock_all", "unlock", "unlock_all",
                     "flush", "flush_all", "flush_local"):
            with pytest.raises(errors.WinError, match="AM component"):
                getattr(win, meth)(0)


class TestHostWindowRw:
    """Round 3: in-process passive target gets real reader-writer
    semantics and identity-checked PSCW (parity with the AM plane)."""

    def test_shared_locks_coexist(self):
        uni = LocalUniverse(4)

        def main(ctx):
            import threading as _t

            buf = np.zeros(1, np.float64)
            win = HostWindow.create(ctx, buf)
            win.fence()
            if ctx.rank == 0:
                # wait until every reader reports holding the lock
                for r in range(1, 4):
                    ctx.recv(source=r, tag=90)
                for r in range(1, 4):
                    ctx.send(b"go", dest=r, tag=91)
            else:
                win.lock(0, 1)  # LOCK_SHARED
                ctx.send(b"held", dest=0, tag=90)
                ctx.recv(source=0, tag=91)  # all held simultaneously
                win.unlock(0)
            win.fence()
            win.free()
            return True

        assert uni.run(main) == [True] * 4

    def test_exclusive_blocks_shared(self):
        uni = LocalUniverse(2)

        def main(ctx):
            buf = np.zeros(1, np.float64)
            win = HostWindow.create(ctx, buf)
            win.fence()
            if ctx.rank == 0:
                win.lock(0, 2)  # EXCLUSIVE on self
                win.put(np.float64(5), 0, 0)
                ctx.send(b"locked", dest=1, tag=92)
                ctx.recv(source=1, tag=93)
                import time

                time.sleep(0.2)  # reader must still be blocked
                win.unlock(0)
                win.fence()
                win.free()
                return None
            ctx.recv(source=0, tag=92)
            ctx.send(b"trying", dest=0, tag=93)
            win.lock(0, 1)  # blocks until rank 0 unlocks
            got = float(win.get(0, 0, 1)[0])
            win.unlock(0)
            win.fence()
            win.free()
            return got

        assert uni.run(main)[1] == 5.0

    def test_pscw_uninvited_origin_does_not_satisfy(self):
        """wait_sync must wait for the POSTED origins, not any N
        completes (identity check)."""
        uni = LocalUniverse(3)

        def main(ctx):
            buf = np.zeros(2, np.float32)
            win = HostWindow.create(ctx, buf)
            if ctx.rank == 0:
                win.post(origins=[2])  # only rank 2 invited
                win.wait_sync(timeout=15.0)
                out = float(buf[0])
                win.free()
                return out
            if ctx.rank == 1:
                # uninvited: a PSCW from a different pairing entirely
                win.free()
                return None
            import time

            time.sleep(0.3)  # let rank 0 wait a moment
            win.start([0])
            win.put(np.float32(9), target=0, offset=0)
            win.complete()
            win.free()
            return None

        assert uni.run(main)[0] == 9.0
