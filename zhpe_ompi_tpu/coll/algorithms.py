"""Collective algorithm library — the heart of the framework.

TPU-native re-design of ``ompi/mca/coll/base`` (SURVEY.md §2.4).  Where the
reference implements each algorithm as a loop of blocking send/recv pairs
driven by the progress engine (e.g. recursive doubling at
``coll_base_allreduce.c:130``, ring at ``:341``, Rabenseifner at ``:970``;
binomial bcast at ``coll_base_bcast.c:329``; pairwise alltoall at
``coll_base_alltoall.c:132``; Bruck allgather at ``coll_base_allgather.c:85``),
here every algorithm is a *static communication schedule* traced once under
``jit``: rounds become ``lax.ppermute`` ops over the ICI mesh, per-rank
divergence becomes ``jnp.where`` masks on the traced rank, and XLA overlaps /
pipelines the rounds.  There is no matching, no fragmentation, no progress
loop — the compiler owns scheduling.

Conventions:

- all functions take ``(comm, x, ...)`` and must be called inside
  ``shard_map`` over the comm's mesh axis;
- ``x`` may be a pytree for the mask-based algorithms (MINLOC/MAXLOC pairs are
  (value, index) tuples); chunked algorithms (ring, Bruck, pairwise) require a
  single dense array;
- patterns are comm-relative and instantiated per sub-group by
  :func:`zhpe_ompi_tpu.pt2pt.spmd.global_pairs` — one XLA op carries every
  sub-communicator of a split;
- mask-based algorithms require a uniform partition (same size per group);
  the components route non-uniform comms to the XLA-native paths.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax

from ..core import errors
from ..pt2pt import spmd


def _where(mask, a, b):
    """Pytree-aware jnp.where with a scalar traced mask."""
    return jax.tree.map(lambda u, v: jnp.where(mask, u, v), a, b)


def _require_uniform(comm) -> int:
    n = comm.uniform_size
    if n is None:
        raise errors.CommError(
            "algorithmic collectives require a uniform partition; "
            "use the xla component for non-uniform splits"
        )
    return n


def _pow2_floor(n: int) -> int:
    return 1 << (n.bit_length() - 1)


# ---------------------------------------------------------------------------
# Allreduce (cf. coll_base_allreduce.c)
# ---------------------------------------------------------------------------


def allreduce_recursive_doubling(comm, x, op):
    """Recursive doubling (reference: coll_base_allreduce.c:130): log2(p)
    exchange rounds; non-power-of-two handled by folding the tail into the
    leading block first (the reference's pow2 adjust at :175-185)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    p2 = _pow2_floor(n)
    extra = n - p2
    if extra:
        recv = spmd.ppermute(comm, x, [(p2 + i, i) for i in range(extra)])
        x = _where(rank < extra, op(recv, x), x)
    k = 1
    while k < p2:
        recv = spmd.ppermute(
            comm, x, [(i, i ^ k) for i in range(p2)]
        )
        x = _where(rank < p2, op(recv, x), x)
        k <<= 1
    if extra:
        recv = spmd.ppermute(comm, x, [(i, p2 + i) for i in range(extra)])
        x = _where(rank >= p2, recv, x)
    return x


def _chunked(x, n):
    """Pad-and-view a dense array as (n, chunk) plus restore info."""
    flat = x.reshape(-1)
    length = flat.shape[0]
    chunk = -(-length // n)  # ceil
    pad = n * chunk - length
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(n, chunk), length


def allreduce_ring(comm, x, op):
    """Ring allreduce: reduce-scatter ring + allgather ring (reference:
    coll_base_allreduce.c:341).  Bandwidth-optimal — 2(p-1)/p of the data
    crosses each link; the shape XLA itself uses for large psums on ICI."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    if not isinstance(x, jax.Array) and not hasattr(x, "shape"):
        raise errors.ArgError("ring allreduce requires a dense array")
    rank = comm.rank()
    buf, length = _chunked(x, n)

    def rs_round(k, b):
        send_idx = (rank - k) % n
        recv_idx = (rank - k - 1) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(op(sent, jnp.take(b, recv_idx, axis=0)))

    buf = lax.fori_loop(0, n - 1, rs_round, buf)

    def ag_round(k, b):
        send_idx = (rank + 1 - k) % n
        recv_idx = (rank - k) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(sent)

    buf = lax.fori_loop(0, n - 1, ag_round, buf)
    return buf.reshape(-1)[:length].reshape(x.shape)


def allreduce_rabenseifner(comm, x, op):
    """Rabenseifner: recursive-halving reduce-scatter + recursive-doubling
    allgather (reference: coll_base_allreduce.c:970).  Power-of-two ranks;
    falls back to ring otherwise — the same guard the reference's decision
    logic applies."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return allreduce_ring(comm, x, op)
    if n == 1:
        return x
    rank = comm.rank()
    buf, length = _chunked(x, n)
    chunk = buf.shape[1]

    # reduce-scatter by recursive halving; rank ends owning chunk `rank`
    lo = jnp.zeros((), jnp.int32)
    bit = n >> 1
    while bit:
        pairs = [(i, i ^ bit) for i in range(n)]
        on_upper = (rank & bit) != 0
        send_lo = jnp.where(on_upper, lo, lo + bit)  # give away other half
        keep_lo = jnp.where(on_upper, lo + bit, lo)
        sent = spmd.ppermute(
            comm, lax.dynamic_slice(buf, (send_lo, 0), (bit, chunk)), pairs
        )
        kept = lax.dynamic_slice(buf, (keep_lo, 0), (bit, chunk))
        buf = lax.dynamic_update_slice(buf, op(sent, kept), (keep_lo, 0))
        lo = keep_lo
        bit >>= 1

    # allgather by recursive doubling
    w = 1
    while w < n:
        pairs = [(i, i ^ w) for i in range(n)]
        my_lo = rank & ~(w - 1)
        partner_lo = (rank ^ w) & ~(w - 1)
        sent = spmd.ppermute(
            comm, lax.dynamic_slice(buf, (my_lo, 0), (w, chunk)), pairs
        )
        buf = lax.dynamic_update_slice(buf, sent, (partner_lo, 0))
        w <<= 1
    return buf.reshape(-1)[:length].reshape(x.shape)


def allreduce_nonoverlapping(comm, x, op):
    """Reduce-then-bcast (reference: coll_base_allreduce.c:54): compose the
    two tree phases; on TPU the value is that the reduce tree and the bcast
    tree use disjoint link directions, so XLA overlaps the tail of one with
    the head of the other."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    reduced = reduce_binomial(comm, x, op, root=0)
    return bcast_binomial(comm, reduced, root=0)


def allreduce_segmented_ring(comm, x, op, segments: int = 4):
    """Segmented ring (reference: coll_base_allreduce.c:618 with its
    ``segment_size`` knob): the message is cut into independent segments,
    each running its own ring.  The reference pipelines segments by hand to
    overlap wire and reduction; here the segment rings share no data
    dependencies, so XLA's scheduler interleaves their ppermutes across ICI
    for the same effect."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    flat = x.reshape(-1)
    length = flat.shape[0]
    segments = max(1, min(segments, max(1, length // n)))
    seg = -(-length // segments)
    pad = segments * seg - length
    if pad:
        flat = jnp.pad(flat, (0, pad))
    parts = [
        allreduce_ring(comm, flat[i * seg : (i + 1) * seg], op)
        for i in range(segments)
    ]
    return jnp.concatenate(parts)[:length].reshape(x.shape)


def allreduce_linear(comm, x, op):
    """Basic linear (reference: coll_base_allreduce.c:881): gather everything
    everywhere, reduce locally in strict rank order — the only algorithm
    whose reduction order matches MPI's canonical order for non-commutative
    ops (rank 0's value ⊕ rank 1's ⊕ ...)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    # stack every rank's contribution: leaf shape (n, *leaf.shape)
    gathered = jax.tree.map(
        lambda a: allgather_ring(comm, jnp.asarray(a)[None]), x
    )

    def block(i):
        return jax.tree.map(lambda g: jnp.take(g, i, axis=0), gathered)

    acc = block(0)
    for i in range(1, n):
        acc = op(acc, block(i))
    return jax.tree.map(
        lambda o, xx: o.reshape(jnp.shape(xx)), acc, x
    )


# ---------------------------------------------------------------------------
# Bcast (cf. coll_base_bcast.c)
# ---------------------------------------------------------------------------


def bcast_binomial(comm, x, root=0):
    """Binomial tree (reference: coll_base_bcast.c:329): round k, virtual
    ranks < 2^k forward to vrank+2^k."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    k = 1
    while k < n:
        pairs = []
        for v in range(min(k, n - k)):
            pairs.append((( v + root) % n, (v + k + root) % n))
        recv = spmd.ppermute(comm, x, pairs)
        x = _where((vrank >= k) & (vrank < 2 * k), recv, x)
        k <<= 1
    return x


def bcast_chain(comm, x, root=0, segments: int = 4):
    """Chain/pipeline bcast (reference: coll_base_bcast.c:273,301): the
    message is cut into segments flowing down a rank chain; XLA overlaps the
    segment ppermutes.  `segments` plays the role of the reference's segsize
    MCA param."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    flat = x.reshape(-1)
    length = flat.shape[0]
    segments = max(1, min(segments, length))
    seg = -(-length // segments)
    pad = segments * seg - length
    if pad:
        flat = jnp.pad(flat, (0, pad))
    segs = flat.reshape(segments, seg)

    # chain pattern in vrank space: v -> v+1; segment s reaches chain
    # position v at step v-1+s, so at step t position v adopts segment
    # s = t - v + 1.  All rounds are static; XLA pipelines the hops.
    pairs = [((v + root) % n, (v + 1 + root) % n) for v in range(n - 1)]
    total_steps = (n - 1) + (segments - 1)

    def step(t, sg):
        sent = spmd.ppermute(comm, sg, pairs)
        s_idx = t - vrank + 1
        adopt = (vrank > 0) & (s_idx >= 0) & (s_idx < segments)
        mask = (jnp.arange(segments) == s_idx) & adopt
        return jnp.where(mask[:, None], sent, sg)

    segs = lax.fori_loop(0, total_steps, step, segs)
    return segs.reshape(-1)[:length].reshape(x.shape)


def bcast_linear(comm, x, root=0):
    """Basic linear bcast (reference: coll_base_bcast.c:624): root sends the
    whole message to each rank individually.  collective_permute patterns
    need unique sources, so the p-1 sends are p-1 independent permutes —
    sharing no data dependencies, XLA schedules them concurrently, which is
    the latency shape of the reference's p-1 non-blocking isends."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    out = x
    for i in range(n):
        if i == root:
            continue
        got = spmd.ppermute(comm, x, [(root, i)])
        out = _where(rank == i, got, out)
    return out


def bcast_binary(comm, x, root=0):
    """Binary-tree bcast (reference: coll_base_bcast.c:245): complete binary
    tree in virtual-rank space (vrank v forwards to 2v+1 and 2v+2), depth
    ceil(log2 p) rounds, two sends per interior node per round."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n

    # level boundaries: nodes [2^d - 1, 2^(d+1) - 1) are at depth d.
    # ppermute needs unique sources, so each level is two permutes — the
    # left-child arm and the right-child arm (independent; XLA overlaps).
    depth = 0
    x_have = x
    while (1 << depth) - 1 < n:
        lo, hi = (1 << depth) - 1, min((1 << (depth + 1)) - 1, n)
        any_pairs = False
        for side in (1, 2):
            pairs = [
                ((v + root) % n, (2 * v + side + root) % n)
                for v in range(lo, hi)
                if 2 * v + side < n
            ]
            if not pairs:
                continue
            any_pairs = True
            recv = spmd.ppermute(comm, x_have, pairs)
            is_child = ((vrank - side) % 2 == 0) & (
                (vrank - side) // 2 >= lo
            ) & ((vrank - side) // 2 < hi) & (vrank >= side)
            x_have = _where(is_child, recv, x_have)
        if not any_pairs:
            break
        depth += 1
    return x_have


def bcast_pipeline(comm, x, root=0, segments: int = 8):
    """Pipelined single-chain bcast (reference: coll_base_bcast.c:273 — the
    chain algorithm with fanout 1): segments stream down one chain, the
    classic latency-hiding shape for large messages.  Delegates to the
    segment-stepping machinery of :func:`bcast_chain`."""
    return bcast_chain(comm, x, root=root, segments=segments)


def bcast_split_binary(comm, x, root=0):
    """Split-binary bcast (reference: coll_base_bcast.c:357): the message is
    split in two halves broadcast down independent trees, followed by a
    pairing exchange.  TPU-native form: the two half-trees are two
    independent static schedules with mirrored round orderings (so they use
    opposing link directions), and XLA overlaps them; the final exchange is
    implicit because both trees span all ranks."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    flat = x.reshape(-1)
    length = flat.shape[0]
    if length < 2:
        return bcast_binomial(comm, x, root)
    half = length // 2
    a = bcast_binomial(comm, flat[:half], root)
    b = bcast_binary(comm, flat[half:], root)
    return jnp.concatenate([a, b]).reshape(x.shape)


def bcast_knomial(comm, x, root=0, radix: int = 4):
    """K-nomial tree bcast (reference: coll_base_bcast.c:714): radix-k
    generalization of binomial — round d, every vrank that is a multiple of
    radix^(d+1) sends to vrank + j*radix^d for j in 1..radix-1.  Fewer
    rounds than binomial (log_k p) at k-1 sends per round; on ICI the k-1
    sends of a round ride one collective_permute."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    if radix < 2:
        raise errors.ArgError(f"knomial radix must be >= 2, got {radix}")
    rank = comm.rank()
    vrank = (rank - root) % n
    # rounds from the top of the tree down: highest stride first
    strides = []
    s = 1
    while s < n:
        strides.append(s)
        s *= radix
    # one permute per child arm j (unique sources per permute); the k-1
    # arms of a round are independent and XLA overlaps them
    for stride in reversed(strides):
        for j in range(1, radix):
            pairs = [
                ((v + root) % n, (v + j * stride + root) % n)
                for v in range(0, n, stride * radix)
                if v + j * stride < n
            ]
            if not pairs:
                continue
            recv = spmd.ppermute(comm, x, pairs)
            is_child = vrank % (stride * radix) == j * stride
            x = _where(is_child, recv, x)
    return x


def bcast_scatter_allgather(comm, x, root=0):
    """Scatter + allgather bcast (reference: coll_base_bcast.c knomial/
    scatter_allgather): binomial scatter of chunks then ring allgather —
    bandwidth-optimal for large messages."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    length = x.size
    # scatter: keep only own chunk (root's data is authoritative)
    own = scatter_linear(comm, x, root)
    gathered = allgather_ring(comm, own)
    return gathered.reshape(-1)[:length].reshape(x.shape)


# ---------------------------------------------------------------------------
# Reduce (cf. coll_base_reduce.c)
# ---------------------------------------------------------------------------


def reduce_binomial(comm, x, op, root=0):
    """Binomial-tree reduce (reference: coll_base_reduce.c:471).  Result is
    significant at root (SPMD: other ranks hold partials)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    k = 1
    while k < n:
        pairs = []
        for v in range(0, n - k, 2 * k):
            pairs.append(((v + k + root) % n, (v + root) % n))
        recv = spmd.ppermute(comm, x, pairs)
        is_recv = (vrank % (2 * k) == 0) & (vrank + k < n)
        # op(x, recv): x holds [v, v+k), recv holds [v+k, v+2k) — keeps
        # the tree's reduction in ascending vrank order
        x = _where(is_recv, op(x, recv), x)
        k <<= 1
    return x


def reduce_linear(comm, x, op, root=0):
    """Linear reduce preserving strict rank order for non-commutative ops."""
    full = allreduce_linear(comm, x, op)
    return full  # every rank computes the rank-ordered result


def reduce_chain(comm, x, op, root=0, segments: int = 4):
    """Chain/pipelined reduce (reference: coll_base_reduce.c:379 chain, :409
    pipeline): partial sums flow down a single chain toward root, segmented
    so the hops of different segments overlap.  Segment chains share no data
    dependencies — XLA interleaves them, which is the pipelining the
    reference hand-schedules."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    flat = x.reshape(-1)
    length = flat.shape[0]
    segments = max(1, min(segments, length))
    seg = -(-length // segments)
    pad = segments * seg - length
    if pad:
        flat = jnp.pad(flat, (0, pad))
    # chain toward root in vrank space: v -> v-1, accumulated at each hop
    pairs = [((v + root) % n, (v - 1 + root) % n) for v in range(1, n)]

    def one_segment(sg):
        def hop(t, acc):
            recv = spmd.ppermute(comm, acc, pairs)
            # at hop t, vrank n-2-t absorbs the partial from vrank n-1-t;
            # op(acc, recv) keeps MPI's rank order x_v ⊕ (x_{v+1} ⊕ ...)
            absorbing = vrank == (n - 2 - t)
            return _where(absorbing, op(acc, recv), acc)

        return lax.fori_loop(0, n - 1, hop, sg)

    parts = [
        one_segment(flat[i * seg : (i + 1) * seg]) for i in range(segments)
    ]
    return jnp.concatenate(parts)[:length].reshape(x.shape)


def reduce_pipeline(comm, x, op, root=0, segments: int = 8):
    """Pipelined reduce (reference: coll_base_reduce.c:409): the chain
    algorithm at higher segment count."""
    return reduce_chain(comm, x, op, root=root, segments=segments)


def reduce_binary(comm, x, op, root=0):
    """Binary-tree reduce (reference: coll_base_reduce.c:440): leaves send
    up a complete binary tree, interior nodes absorb both children per
    round (one collective_permute per child side)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    # deepest level d has nodes [2^d - 1, 2^(d+1) - 1) ∩ [0, n)
    max_depth = 0
    while (1 << (max_depth + 1)) - 1 < n:
        max_depth += 1
    for d in range(max_depth, 0, -1):
        lo, hi = (1 << d) - 1, min((1 << (d + 1)) - 1, n)
        for side in (1, 2):  # vrank 2p+1 is p's left child, 2p+2 its right
            pairs = [
                ((v + root) % n, ((v - side) // 2 + root) % n)
                for v in range(lo, hi)
                if (v - side) % 2 == 0
            ]
            if not pairs:
                continue
            recv = spmd.ppermute(comm, x, pairs)
            is_parent = (2 * vrank + side >= lo) & (2 * vrank + side < hi)
            x = _where(is_parent, op(x, recv), x)
    return x


def reduce_in_order_binary(comm, x, op, root=0):
    """In-order binary reduce (reference: coll_base_reduce.c:509): exists to
    give non-commutative ops a deterministic reduction order.  On SPMD the
    rank-ordered guarantee is provided by the linear algorithm (the only
    order MPI defines), so this delegates — the reference's in-order tree is
    an optimization of the same contract."""
    return reduce_linear(comm, x, op, root)


def reduce_rabenseifner(comm, x, op, root=0):
    """Rabenseifner reduce (reference: coll_base_reduce.c:797): recursive
    -halving reduce-scatter + binomial gather to root.  SPMD form: after the
    reduce-scatter each rank owns one reduced chunk; the gather is an
    allgather (result significant at root), which on ICI is the faster
    primitive anyway."""
    n = _require_uniform(comm)
    if n & (n - 1) or n == 1:
        return reduce_binomial(comm, x, op, root)
    buf, length = _chunked(x, n)
    own = reduce_scatter_recursive_halving(comm, buf.reshape(-1), op)
    gathered = allgather_ring(comm, own)
    return gathered.reshape(-1)[:length].reshape(x.shape)


# ---------------------------------------------------------------------------
# Allgather (cf. coll_base_allgather.c)
# ---------------------------------------------------------------------------


def _stack_shape(x):
    return x[None] if x.ndim == 0 else x


def allgather_ring(comm, x):
    """Ring allgather (reference: coll_base_allgather.c:358)."""
    n = _require_uniform(comm)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice(buf, x[None], (rank,) + (0,) * x.ndim)

    def ag_round(k, b):
        send_idx = (rank - k) % n
        recv_idx = (rank - k - 1) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(sent)

    buf = lax.fori_loop(0, n - 1, ag_round, buf)
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def allgather_bruck(comm, x):
    """Bruck allgather (reference: coll_base_allgather.c:85): ceil(log2 p)
    rounds of doubling block counts, then a rotation."""
    n = _require_uniform(comm)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = buf.at[0].set(x)
    k = 1
    while k < n:
        cnt = min(k, n - k)
        send = buf[:cnt]  # static slice
        recv = spmd.ppermute(
            comm, send, lambda m, k=k: [(i, (i - k) % m) for i in range(m)]
        )
        buf = lax.dynamic_update_slice(
            buf, recv, (k,) + (0,) * (buf.ndim - 1)
        )
        k <<= 1
    # buf[j] holds the block of comm rank (rank + j) % n; rotate to rank order
    buf = jnp.roll(buf, shift=rank, axis=0)
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def allgather_recursive_doubling(comm, x):
    """Recursive-doubling allgather (pow2; reference pattern of
    coll_base_allgather.c). Falls back to Bruck otherwise."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return allgather_bruck(comm, x)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice(buf, x[None], (rank,) + (0,) * x.ndim)
    w = 1
    while w < n:
        pairs = [(i, i ^ w) for i in range(n)]
        my_lo = rank & ~(w - 1)
        partner_lo = (rank ^ w) & ~(w - 1)
        sent = spmd.ppermute(
            comm,
            lax.dynamic_slice(
                buf, (my_lo,) + (0,) * x.ndim, (w,) + x.shape
            ),
            pairs,
        )
        buf = lax.dynamic_update_slice(
            buf, sent, (partner_lo,) + (0,) * x.ndim
        )
        w <<= 1
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def _neighbor_exchange_plan(n: int):
    """Static per-step (pairs, sent_lo[rank], recv_lo[rank]) tables for the
    neighbor-exchange allgather — computed once in Python since n is static
    under jit."""
    sent = [r - (r % 2) for r in range(n)]  # pair window owned after step 0
    steps = []
    for s in range(1, n // 2):
        partner = []
        for r in range(n):
            if r % 2 == 0:
                p = (r - 1) % n if s % 2 == 1 else (r + 1) % n
            else:
                p = (r + 1) % n if s % 2 == 1 else (r - 1) % n
            partner.append(p)
        pairs = [(r, partner[r]) for r in range(n)]
        recv = [sent[partner[r]] for r in range(n)]
        steps.append((pairs, list(sent), list(recv)))
        sent = recv
    return steps


def allgather_neighbor_exchange(comm, x):
    """Neighbor-exchange allgather (reference: coll_base_allgather.c:484,
    the Chen et al. algorithm): even n only — n/2 rounds alternating
    exchanges with left/right neighbors, each carrying the pair-window
    received in the previous round.  Falls back to ring for odd n, as the
    reference's selection logic does."""
    n = _require_uniform(comm)
    x = _stack_shape(x)
    if n == 1:
        return x
    if n % 2:
        return allgather_ring(comm, x)
    rank = comm.rank()
    zero_idx = (0,) * x.ndim
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice(buf, x[None], (rank,) + zero_idx)
    # step 0: exchange own block within (even, odd) pairs
    recv0 = spmd.ppermute(comm, x, [(i, i ^ 1) for i in range(n)])
    buf = lax.dynamic_update_slice(buf, recv0[None], (rank ^ 1,) + zero_idx)
    for pairs, sent_lo, recv_lo in _neighbor_exchange_plan(n):
        s_lo = jnp.take(jnp.asarray(sent_lo), rank)
        r_lo = jnp.take(jnp.asarray(recv_lo), rank)
        win = lax.dynamic_slice(buf, (s_lo,) + zero_idx, (2,) + x.shape)
        got = spmd.ppermute(comm, win, pairs)
        buf = lax.dynamic_update_slice(buf, got, (r_lo,) + zero_idx)
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def allgather_two_proc(comm, x):
    """Two-process allgather (reference: coll_base_allgather.c:598): one
    exchange.  Requires comm size 2; falls back to ring otherwise."""
    n = _require_uniform(comm)
    if n != 2:
        return allgather_ring(comm, x)
    x = _stack_shape(x)
    rank = comm.rank()
    other = spmd.ppermute(comm, x, [(0, 1), (1, 0)])
    lo = _where(rank == 0, x, other)
    hi = _where(rank == 0, other, x)
    return jnp.concatenate([lo, hi], axis=0)


def allgather_linear(comm, x):
    """Basic linear allgather (reference: coll_base_allgather.c:681): every
    rank sends to every other.  The reference posts p(p-1) point-to-points;
    here it is p-1 independent shift permutes that XLA schedules
    concurrently — latency-optimal for tiny payloads on ICI."""
    n = _require_uniform(comm)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    buf = jnp.zeros((n,) + x.shape, x.dtype)
    buf = lax.dynamic_update_slice(buf, x[None], (rank,) + (0,) * x.ndim)
    for r in range(1, n):
        got = spmd.shift(comm, x, r, wrap=True)
        src = (rank - r) % n
        buf = lax.dynamic_update_slice(
            buf, got[None], (src,) + (0,) * x.ndim
        )
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


# ---------------------------------------------------------------------------
# Alltoall (cf. coll_base_alltoall.c)
# ---------------------------------------------------------------------------


def _atoall_blocks(comm, x):
    n = _require_uniform(comm)
    if x.shape[0] % n:
        raise errors.CountError(
            f"alltoall needs dim0 divisible by comm size {n}, got {x.shape[0]}"
        )
    m = x.shape[0] // n
    return n, x.reshape((n, m) + x.shape[1:])


def alltoall_pairwise(comm, x):
    """Pairwise exchange (reference: coll_base_alltoall.c:132): p-1 rounds,
    round r exchanges with rank±r."""
    n, blocks = _atoall_blocks(comm, x)
    if n == 1:
        return x
    rank = comm.rank()
    out = jnp.zeros_like(blocks)
    out = out.at[rank].set(jnp.take(blocks, rank, axis=0))

    def round_r(r, o):
        sendto = (rank + r) % n
        recvfrom = (rank - r) % n
        sent = spmd.ppermute(
            comm, jnp.take(blocks, sendto, axis=0),
            lambda m, r=r: [(i, (i + r) % m) for i in range(m)],
        )
        return o.at[recvfrom].set(sent)

    # r is traced inside fori_loop but the ppermute pattern depends on it,
    # so unroll the (static-count) rounds instead.
    for r in range(1, n):
        out = round_r(r, out)
    return out.reshape(x.shape)


def alltoall_bruck(comm, x):
    """Bruck alltoall (reference: coll_base_alltoall.c:191): log2(p) rounds
    moving blocks whose index has bit k set; saves latency for small
    messages at the cost of local rotations."""
    n, blocks = _atoall_blocks(comm, x)
    if n == 1:
        return x
    rank = comm.rank()
    # phase 1: local rotation so block j targets rank (rank + j) % n
    blocks = jnp.roll(blocks, shift=-rank, axis=0)
    # phase 2: for each bit k, send blocks with bit k set to rank + 2^k
    k = 1
    while k < n:
        mask = (jnp.arange(n) & k) != 0
        sent = spmd.ppermute(
            comm, blocks, lambda m, k=k: [(i, (i + k) % m) for i in range(m)]
        )
        blocks = jnp.where(
            mask.reshape((n,) + (1,) * (blocks.ndim - 1)), sent, blocks
        )
        k <<= 1
    # phase 3: after phase 2, slot j at rank d holds data from source
    # (d - j) mod n; restoring source order is a flip then rotate by rank+1
    blocks = jnp.roll(jnp.flip(blocks, axis=0), shift=rank + 1, axis=0)
    return blocks.reshape(x.shape)


def alltoall_linear(comm, x):
    """Basic linear alltoall (reference: coll_base_alltoall.c:569): post
    everything at once.  On SPMD the posting-order distinction between
    linear and pairwise vanishes — both lower to the same p-1 static shift
    permutes, which XLA is free to schedule concurrently — so this shares
    pairwise's schedule."""
    return alltoall_pairwise(comm, x)


def alltoall_linear_sync(comm, x, window: int = 4):
    """Linear-sync alltoall (reference: coll_base_alltoall.c:333): like
    linear but with at most `window` transfers in flight.  The TPU analog of
    the in-flight cap is a data-dependency barrier between batches of
    `window` rounds, bounding concurrent ICI traffic (useful when the
    alltoall shares the mesh with other collectives)."""
    n, blocks = _atoall_blocks(comm, x)
    if n == 1:
        return x
    rank = comm.rank()
    out = jnp.zeros_like(blocks)
    out = out.at[rank].set(jnp.take(blocks, rank, axis=0))
    token = jnp.zeros((), blocks.dtype)
    for r in range(1, n):
        sendto = (rank + r) % n
        recvfrom = (rank - r) % n
        payload = jnp.take(blocks, sendto, axis=0) + token
        sent = spmd.ppermute(
            comm, payload,
            lambda m, r=r: [(i, (i + r) % m) for i in range(m)],
        )
        out = out.at[recvfrom].set(sent)
        if r % window == 0:
            # serialize the next batch behind this one; the zero tie-in is a
            # *float* mul-by-zero — integer x*0 would be constant-folded and
            # the window cap silently lost (see _barrier_token)
            token = (
                jnp.sum(sent).astype(jnp.float32) * 0.0
            ).astype(blocks.dtype)
    return out.reshape(x.shape)


def alltoall_two_proc(comm, x):
    """Two-process alltoall (reference: coll_base_alltoall.c:490): one
    exchange of the off-diagonal blocks."""
    n, blocks = _atoall_blocks(comm, x)
    if n != 2:
        return alltoall_pairwise(comm, x)
    rank = comm.rank()
    mine = jnp.take(blocks, rank, axis=0)
    theirs = spmd.ppermute(
        comm, jnp.take(blocks, 1 - rank, axis=0), [(0, 1), (1, 0)]
    )
    lo = _where(rank == 0, mine, theirs)
    hi = _where(rank == 0, theirs, mine)
    return jnp.stack([lo, hi]).reshape(x.shape)


# ---------------------------------------------------------------------------
# Alltoallv (cf. coll_base_alltoallv.c)
# ---------------------------------------------------------------------------


def alltoallv_prepare(comm, x, counts):
    """Shared front half of every alltoallv transport: validate the static
    ``counts[i][j]`` matrix, pad the send blocks to the global max count,
    and zero-mask rows beyond this rank's per-destination counts so
    padding can never leak into receive buffers.  Returns
    ``(blocks, max_recv)`` with blocks shaped ``(n, max_recv, ...)``."""
    n = _require_uniform(comm)
    if len(counts) != n or any(
        not hasattr(row, "__len__") or len(row) != n for row in counts
    ):
        raise errors.ArgError(f"counts must be {n}x{n}")
    if x.shape[0] != n:
        raise errors.CountError(
            f"alltoallv send buffer needs {n} blocks, got {x.shape[0]}"
        )
    rank = comm.rank()
    max_recv = max(max(row) for row in counts)
    blk = x.shape[1]
    if blk < max_recv:
        x = jnp.pad(
            x, ((0, 0), (0, max_recv - blk)) + ((0, 0),) * (x.ndim - 2)
        )
    else:
        x = x[:, :max_recv]
    sent_cnt = jnp.asarray(counts)[rank]  # (n,) rows sent to each dest
    mask = jnp.arange(max_recv)[None, :] < sent_cnt[:, None]
    x = jnp.where(
        mask.reshape((n, max_recv) + (1,) * (x.ndim - 2)), x,
        jnp.zeros_like(x),
    )
    return x, max_recv


def alltoallv_padded(comm, x, counts):
    """Pairwise alltoallv (reference: coll_base_alltoallv.c:125) with a
    static count matrix.  ``counts[i][j]`` is how many dim0 rows rank i
    sends to rank j (known to all ranks — the SPMD analog of every rank's
    sendcounts array).  ``x`` is this rank's send buffer laid out as
    ``(n, max_send, ...)`` padded blocks.  Returns ``(n, max_recv, ...)``
    padded receive blocks — entries beyond ``counts[src][rank]`` are zero.
    Static padding is the price of static shapes; the communicator layer
    offers the ragged reassembly."""
    blocks, max_recv = alltoallv_prepare(comm, x, counts)
    n = _require_uniform(comm)
    rank = comm.rank()
    out = jnp.zeros_like(blocks)
    out = lax.dynamic_update_slice(
        out, jnp.take(blocks, rank, axis=0)[None],
        (rank,) + (0,) * (out.ndim - 1),
    )
    for r in range(1, n):
        sendto = (rank + r) % n
        recvfrom = (rank - r) % n
        sent = spmd.ppermute(
            comm, jnp.take(blocks, sendto, axis=0),
            lambda m, r=r: [(i, (i + r) % m) for i in range(m)],
        )
        out = lax.dynamic_update_slice(
            out, sent[None], (recvfrom,) + (0,) * (out.ndim - 1)
        )
    return out


# ---------------------------------------------------------------------------
# Reduce_scatter (cf. coll_base_reduce_scatter.c)
# ---------------------------------------------------------------------------


def reduce_scatter_ring(comm, x, op):
    """Ring reduce-scatter (reference: coll_base_reduce_scatter.c:456)."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    n_, blocks = _atoall_blocks(comm, x)

    def rs_round(k, b):
        send_idx = (rank - k) % n
        recv_idx = (rank - k - 1) % n
        sent = spmd.ppermute(
            comm, jnp.take(b, send_idx, axis=0),
            lambda m: [(i, (i + 1) % m) for i in range(m)],
        )
        return b.at[recv_idx].set(op(sent, jnp.take(b, recv_idx, axis=0)))

    blocks = lax.fori_loop(0, n - 1, rs_round, blocks)
    # rank owns chunk (rank+1)%n; shift it home so rank r holds chunk r
    owned = jnp.take(blocks, (rank + 1) % n, axis=0)
    return spmd.shift(comm, owned, 1, wrap=True)


def reduce_scatter_recursive_halving(comm, x, op):
    """Recursive halving (reference: coll_base_reduce_scatter.c:132); pow2
    ranks, falls back to ring otherwise."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return reduce_scatter_ring(comm, x, op)
    if n == 1:
        return x
    rank = comm.rank()
    _, blocks = _atoall_blocks(comm, x)
    shape_rest = blocks.shape[1:]
    lo = jnp.zeros((), jnp.int32)
    bit = n >> 1
    while bit:
        pairs = [(i, i ^ bit) for i in range(n)]
        on_upper = (rank & bit) != 0
        send_lo = jnp.where(on_upper, lo, lo + bit)
        keep_lo = jnp.where(on_upper, lo + bit, lo)
        sent = spmd.ppermute(
            comm,
            lax.dynamic_slice(
                blocks, (send_lo,) + (0,) * len(shape_rest), (bit,) + shape_rest
            ),
            pairs,
        )
        kept = lax.dynamic_slice(
            blocks, (keep_lo,) + (0,) * len(shape_rest), (bit,) + shape_rest
        )
        blocks = lax.dynamic_update_slice(
            blocks, op(sent, kept), (keep_lo,) + (0,) * len(shape_rest)
        )
        lo = keep_lo
        bit >>= 1
    return jnp.take(blocks, rank, axis=0)


def reduce_scatter_nonoverlapping(comm, x, op):
    """Reduce + scatter composition (reference:
    coll_base_reduce_scatter.c:47): binomial reduce to rank 0, then linear
    scatter of the chunks."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    _atoall_blocks(comm, x)  # validate divisibility
    reduced = reduce_binomial(comm, x, op, root=0)
    chunk = x.shape[0] // n
    scattered = scatter_linear(comm, reduced.reshape(-1), 0)
    return scattered[: chunk * math.prod(x.shape[1:])].reshape(
        (chunk,) + x.shape[1:]
    )


def reduce_scatter_butterfly(comm, x, op):
    """Butterfly reduce-scatter (reference: coll_base_reduce_scatter.c:691).
    For power-of-two comms the butterfly's pairwise distance-halving
    exchange coincides with recursive halving; the reference's extra
    machinery exists to handle non-power-of-two ranks, which here (as in
    Rabenseifner) falls back to the ring."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return reduce_scatter_ring(comm, x, op)
    return reduce_scatter_recursive_halving(comm, x, op)


# ---------------------------------------------------------------------------
# Reduce_scatter_block (cf. coll_base_reduce_scatter_block.c)
# ---------------------------------------------------------------------------
# MPI_Reduce_scatter_block: equal recvcounts — exactly the contract the
# chunked algorithms above already implement, so the block entry points are
# the canonical ones and MPI_Reduce_scatter with uniform counts delegates
# here.


def reduce_scatter_block_linear(comm, x, op):
    """Reduce-to-all then take own block (reference:
    coll_base_reduce_scatter_block.c:55 reduce+scatter via rank order)."""
    n = _require_uniform(comm)
    _, blocks = _atoall_blocks(comm, x)
    full = allreduce_linear(comm, x, op)
    return jnp.take(
        full.reshape((n,) + blocks.shape[1:]), comm.rank(), axis=0
    )


def reduce_scatter_block_recursive_doubling(comm, x, op):
    """Recursive-doubling variant (reference:
    coll_base_reduce_scatter_block.c:128): allreduce by recursive doubling,
    keep own block — latency-optimal for small payloads."""
    n = _require_uniform(comm)
    _, blocks = _atoall_blocks(comm, x)
    full = allreduce_recursive_doubling(comm, x, op)
    return jnp.take(
        full.reshape((n,) + blocks.shape[1:]), comm.rank(), axis=0
    )


def reduce_scatter_block_recursive_halving(comm, x, op):
    """Recursive-halving variant (reference:
    coll_base_reduce_scatter_block.c:326)."""
    return reduce_scatter_recursive_halving(comm, x, op)


def reduce_scatter_block_butterfly(comm, x, op):
    """Butterfly variant (reference: coll_base_reduce_scatter_block.c:567
    and the pow2 specialization at :810)."""
    return reduce_scatter_butterfly(comm, x, op)


# ---------------------------------------------------------------------------
# Scan / Exscan (cf. coll_base_scan.c, coll_base_exscan.c)
# ---------------------------------------------------------------------------


def scan_recursive_doubling(comm, x, op):
    """Inclusive prefix reduction, Hillis-Steele over ranks (reference:
    coll_base_scan.c:157).  Order-preserving: safe for non-commutative
    (associative) ops."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    k = 1
    while k < n:
        recv = spmd.ppermute(
            comm, x, [(i, i + k) for i in range(n - k)]
        )
        x = _where(rank >= k, op(recv, x), x)
        k <<= 1
    return x


def scan_linear(comm, x, op):
    """Linear scan (reference: coll_base_scan.c:35): the running prefix
    crawls up the rank chain one hop per round — n-1 rounds, each a single
    point-to-point.  Exists for forced selection and as the semantic
    baseline; recursive doubling is the performant choice."""
    n = _require_uniform(comm)
    if n == 1:
        return x
    rank = comm.rank()
    acc = x
    for r in range(1, n):
        recv = spmd.ppermute(comm, acc, [(r - 1, r)])
        acc = _where(rank == r, op(recv, acc), acc)
    return acc


def exscan_linear(comm, x, op):
    """Linear exscan (reference: coll_base_exscan.c:35): the inclusive
    prefix of rank r-1 arrives as rank r's exclusive result."""
    n = _require_uniform(comm)
    if n == 1:
        return jax.tree.map(jnp.zeros_like, x)
    rank = comm.rank()
    acc = x                     # inclusive prefix being built
    out = jax.tree.map(jnp.zeros_like, x)
    for r in range(1, n):
        recv = spmd.ppermute(comm, acc, [(r - 1, r)])
        out = _where(rank == r, recv, out)
        acc = _where(rank == r, op(recv, acc), acc)
    return out


def exscan_recursive_doubling(comm, x, op):
    """Exclusive scan (reference: coll_base_exscan.c:142): inclusive scan,
    then shift the RESULTS up one rank — correct for every associative op
    (shifting inputs instead would inject ppermute's zero-fill at rank 0
    into every prefix, which is only an identity for SUM).  Rank 0's result
    is undefined per MPI; here it holds zeros."""
    _require_uniform(comm)
    inclusive = scan_recursive_doubling(comm, x, op)
    return spmd.shift(comm, inclusive, 1, wrap=False)


# ---------------------------------------------------------------------------
# Barrier (cf. coll_base_barrier.c)
# ---------------------------------------------------------------------------


def _barrier_token(comm, token):
    """The scalar each barrier round actually permutes.

    Three elimination traps, all verified against the XLA CPU pipeline:
    integer ``sum(token) * 0`` is algebraically folded to a literal; a
    collective-permute whose operand is a provably-constant splat is folded
    (zeros in, zeros out), taking the whole barrier with it; and
    ``optimization_barrier`` does not help because JAX's jaxpr-level DCE
    prunes its unused outputs together with their operands.  So the wire
    payload is *float32* and runtime-variant — axis_index (partition id)
    plus the caller's token data — and :func:`_seal_token` turns the final
    value into zero with a float mul-by-zero, which XLA must keep (0*x is
    NaN for x=NaN/Inf, so floats never fold)."""
    t = comm.axis_index().astype(jnp.float32)
    if token is not None:
        t = t + jnp.sum(token).astype(jnp.float32)
    return t


def _seal_token(t):
    """An int32 zero whose value genuinely flows from the barrier rounds
    (see :func:`_barrier_token` for why this is a float multiply).  NaN in
    the caller's token would poison the zero — garbage in, garbage out, as
    with any data-dependent sequencing."""
    return (t.astype(jnp.float32) * 0.0).astype(jnp.int32)


def barrier_dissemination(comm, token=None):
    """Bruck/dissemination barrier (reference: coll_base_barrier.c:253):
    ceil(log2 p) rounds of shifted notifications.  Returns a data-dependent
    zero scalar usable as a sequencing token."""
    n = _require_uniform(comm)
    t = _barrier_token(comm, token)
    k = 1
    while k < n:
        t = t + spmd.ppermute(
            comm, t, lambda m, k=k: [(i, (i + k) % m) for i in range(m)]
        )
        k <<= 1
    return _seal_token(t)


def barrier_double_ring(comm, token=None):
    """Double-ring barrier (reference: coll_base_barrier.c:100): two full
    laps of a unit token around the ring — 2(p-1) hops, the simplest
    schedule that transitively orders every rank."""
    n = _require_uniform(comm)
    t = _barrier_token(comm, token)

    def hop(_, tok):
        # pass the token along (no accumulation: tok + shift(tok) doubles
        # per hop and overflows f32 to inf around 60 ranks, NaN-poisoning
        # the seal); each hop depends on the left neighbor's previous hop,
        # so n-1 laps transitively order every rank
        return spmd.shift(comm, tok, 1, wrap=True)

    return _seal_token(lax.fori_loop(0, 2 * (n - 1), hop, t))


def barrier_recursive_doubling(comm, token=None):
    """Recursive-doubling barrier (reference: coll_base_barrier.c:172):
    log2(p) pairwise xor-distance exchanges (pow2 comms; dissemination
    handles the rest and is what non-pow2 falls back to)."""
    n = _require_uniform(comm)
    if n & (n - 1):
        return barrier_dissemination(comm, token)
    t = _barrier_token(comm, token)
    k = 1
    while k < n:
        t = t + spmd.ppermute(comm, t, [(i, i ^ k) for i in range(n)])
        k <<= 1
    return _seal_token(t)


def barrier_two_proc(comm, token=None):
    """Two-process barrier (reference: coll_base_barrier.c:291): one
    exchange."""
    n = _require_uniform(comm)
    if n != 2:
        return barrier_dissemination(comm, token)
    t = _barrier_token(comm, token)
    return _seal_token(t + spmd.ppermute(comm, t, [(0, 1), (1, 0)]))


def barrier_tree(comm, token=None):
    """Tree barrier (reference: coll_base_barrier.c:404): binomial fan-in to
    rank 0 then binomial fan-out — the reduce/bcast trees applied to a unit
    token."""
    _require_uniform(comm)
    t = _barrier_token(comm, token)
    t = reduce_binomial(comm, t, lambda a, b: a + b, root=0)
    return _seal_token(bcast_binomial(comm, t, root=0))


def barrier_linear(comm, token=None):
    """Linear barrier (reference: coll_base_barrier.c:330): everyone
    reports to everyone.  The reference funnels through rank 0; the SPMD
    equivalent of "rank 0 heard from all, then told all" with static
    patterns is the all-to-all notification, p-1 concurrent permutes."""
    n = _require_uniform(comm)
    t = _barrier_token(comm, token)
    acc = t
    for r in range(1, n):
        acc = acc + spmd.shift(comm, t, r, wrap=True)
    return _seal_token(acc)


# ---------------------------------------------------------------------------
# Gather / Scatter (cf. coll_base_gather.c / coll_base_scatter.c)
# ---------------------------------------------------------------------------


def gather_ring(comm, x, root=0):
    """Gather via allgather.  SPMD note (documented semantic): on a
    single-program machine every device executes the same collective, so the
    "only root receives" optimization of the reference's binomial gather
    (coll_base_gather.c:41) buys nothing — the result is simply significant
    at root."""
    return allgather_ring(comm, x)


def scatter_linear(comm, x, root=0):
    """Linear scatter (reference: coll_base_scatter.c:63): root sends chunk i
    to rank i, one static ppermute per destination; XLA overlaps them."""
    n = _require_uniform(comm)
    buf, length = _chunked(x, n)
    chunk = buf.shape[1]
    rank = comm.rank()
    out = jnp.take(buf, rank, axis=0)  # root's own chunk (and garbage elsewhere)
    for i in range(n):
        if i == root:
            continue
        sent = spmd.ppermute(comm, buf[i], [(root, i)])
        out = _where(rank == i, sent, out)
    # non-root ranks' x may be garbage; out at rank i is root's chunk i
    return out


def gather_binomial(comm, x, root=0):
    """Binomial-tree gather (reference: coll_base_gather.c:41): round k,
    vranks with bit k set ship their accumulated window of k blocks to
    vrank−k; root ends holding all p blocks.  The windows are dynamic
    slices at traced offsets with static sizes — jit-compatible.  Result is
    the full (p·m, ...) buffer, significant at root."""
    n = _require_uniform(comm)
    x = _stack_shape(x)
    if n == 1:
        return x
    rank = comm.rank()
    vrank = (rank - root) % n
    zero_idx = (0,) * x.ndim
    # 2n rows so window reads/writes past n land in the zero pad instead of
    # being clamped by dynamic_slice (non-pow2 tails)
    buf = jnp.zeros((2 * n,) + x.shape, x.dtype)
    # each rank's accumulated window starts at its own vrank
    buf = lax.dynamic_update_slice(buf, x[None], (vrank,) + zero_idx)
    k = 1
    while k < n:
        pairs = [
            ((v + k + root) % n, (v + root) % n)
            for v in range(0, n - k, 2 * k)
        ]
        sent = spmd.ppermute(
            comm,
            lax.dynamic_slice(buf, (vrank,) + zero_idx, (k,) + x.shape),
            pairs,
        )
        is_recv = (vrank % (2 * k) == 0) & (vrank + k < n)
        merged = lax.dynamic_update_slice(
            buf, sent, (vrank + k,) + zero_idx
        )
        buf = _where(is_recv, merged, buf)
        k <<= 1
    # root's window is [0, n) in vrank order; rotate to rank order
    buf = jnp.roll(buf[:n], shift=root, axis=0)
    return buf.reshape((n * x.shape[0],) + x.shape[1:])


def gather_linear_sync(comm, x, root=0):
    """Linear-sync gather (reference: coll_base_gather.c:208): the
    reference rate-limits senders with an ack handshake; on a statically
    scheduled machine the collective_permutes already execute in schedule
    order, so this shares the ring-gather schedule."""
    return gather_ring(comm, x, root)


def scatter_binomial(comm, x, root=0):
    """Binomial-tree scatter (reference: coll_base_scatter.c:63, the
    binomial entry): the mirror of binomial gather — root starts with all p
    chunks, round k (descending) hands the upper half of each holder's
    window to vrank+k.  Dynamic windows at traced offsets, static sizes."""
    n = _require_uniform(comm)
    buf, length = _chunked(x, n)
    chunk = buf.shape[1]
    if n == 1:
        return buf.reshape(-1)[:length]
    rank = comm.rank()
    vrank = (rank - root) % n
    # rotate root's buffer into vrank order, then pad to 2n rows so window
    # reads past n hit the zero pad instead of dynamic_slice clamping
    buf = jnp.roll(buf, shift=-root, axis=0)
    buf = jnp.concatenate([buf, jnp.zeros_like(buf)], axis=0)
    k = _pow2_floor(n - 1) if n > 1 else 0
    while k >= 1:
        pairs = [
            ((v + root) % n, (v + k + root) % n)
            for v in range(0, n - k, 2 * k)
        ]
        sent = spmd.ppermute(
            comm,
            lax.dynamic_slice(buf, (vrank + k, 0), (k, chunk)),
            pairs,
        )
        is_recv = vrank % (2 * k) == k
        merged = lax.dynamic_update_slice(buf, sent, (vrank, 0))
        buf = _where(is_recv, merged, buf)
        k >>= 1
    return jnp.take(buf, vrank, axis=0)


def bcast_via_scatter(comm, x, root=0):
    return bcast_scatter_allgather(comm, x, root)


# ---------------------------------------------------------------------------
# Vector (v) variants
# ---------------------------------------------------------------------------


def allgatherv_concat(comm, x, counts: list[int]):
    """Allgatherv with static per-rank counts (cf. coll_base_allgatherv.c):
    pad to the max count, exchange, then statically re-concatenate.  `x` is
    this device's contribution, whose dim0 may be any value up to
    max(counts); entries beyond the device's count are ignored."""
    n = _require_uniform(comm)
    if len(counts) != n:
        raise errors.ArgError(f"need {n} counts, got {len(counts)}")
    mx = max(counts)
    pad = mx - x.shape[0]
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    gathered = allgather_ring(comm, x).reshape((n, mx) + x.shape[1:])
    parts = [gathered[i, : counts[i]] for i in range(n)]
    return jnp.concatenate(parts, axis=0)
