/* misc2_c.c — round-5 batch-8 acceptance: group range algebra and
 * compare, MPI-1 attribute names, datatype attributes, persistent
 * send modes, request-based RMA, canonical external32 packing,
 * size-matched and f90-parameterized types, generalized requests.
 * Reference shapes: ompi/mpi/c/{group_range_incl,group_compare,
 * attr_put,type_create_keyval,ssend_init,rput,pack_external,
 * type_match_size,grequest_start}.c.  Run with >= 2 ranks. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include "zompi_mpi.h"

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);  \
      MPI_Abort(MPI_COMM_WORLD, 2);                                    \
    }                                                                  \
  } while (0)

static int type_del_calls = 0;
static int type_del_fn(MPI_Datatype d, int k, void *v, void *es) {
  (void)d; (void)k; (void)v; (void)es;
  type_del_calls++;
  return MPI_SUCCESS;
}

static int gq_query(void *extra, MPI_Status *st) {
  *(int *)extra += 1;
  st->_count = 42;
  return MPI_SUCCESS;
}
static int gq_free(void *extra) {
  *(int *)extra += 100;
  return MPI_SUCCESS;
}
static int gq_cancel(void *extra, int complete) {
  (void)extra; (void)complete;
  return MPI_SUCCESS;
}

int main(int argc, char **argv) {
  CHECK(MPI_Init(&argc, &argv) == MPI_SUCCESS);
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  CHECK(size >= 2);

  /* ---- group range algebra + compare ---- */
  {
    MPI_Group w, evens, evens2, rest;
    CHECK(MPI_Comm_group(MPI_COMM_WORLD, &w) == MPI_SUCCESS);
    int r1[1][3] = {{0, size - 1, 2}};
    CHECK(MPI_Group_range_incl(w, 1, r1, &evens) == MPI_SUCCESS);
    int esz = -1;
    CHECK(MPI_Group_size(evens, &esz) == MPI_SUCCESS);
    CHECK(esz == (size + 1) / 2);
    /* same membership built by excluding the odds */
    int r2[1][3] = {{1, size - 1, 2}};
    CHECK(MPI_Group_range_excl(w, 1, r2, &evens2) == MPI_SUCCESS);
    int cmp = -1;
    CHECK(MPI_Group_compare(evens, evens2, &cmp) == MPI_SUCCESS);
    CHECK(cmp == MPI_IDENT);
    /* reversed order is SIMILAR, not IDENT */
    int r3[1][3] = {{size - 1 - (size - 1) % 2, 0, -2}};
    CHECK(MPI_Group_range_incl(w, 1, r3, &rest) == MPI_SUCCESS);
    CHECK(MPI_Group_compare(evens, rest, &cmp) == MPI_SUCCESS);
    CHECK(cmp == (esz > 1 ? MPI_SIMILAR : MPI_IDENT));
    CHECK(MPI_Group_compare(evens, w, &cmp) == MPI_SUCCESS);
    CHECK(size == esz ? cmp == MPI_IDENT : cmp == MPI_UNEQUAL);
    MPI_Group_free(&evens);
    MPI_Group_free(&evens2);
    MPI_Group_free(&rest);
    MPI_Group_free(&w);
  }

  /* ---- MPI-1 attribute names ---- */
  {
    int kv = MPI_KEYVAL_INVALID;
    CHECK(MPI_Keyval_create(NULL, NULL, &kv, NULL) == MPI_SUCCESS);
    CHECK(MPI_Attr_put(MPI_COMM_WORLD, kv, (void *)0xCAFE) ==
          MPI_SUCCESS);
    void *got = NULL;
    int found = 0;
    CHECK(MPI_Attr_get(MPI_COMM_WORLD, kv, &got, &found) == MPI_SUCCESS);
    CHECK(found == 1 && got == (void *)0xCAFE);
    CHECK(MPI_Attr_delete(MPI_COMM_WORLD, kv) == MPI_SUCCESS);
    CHECK(MPI_Attr_get(MPI_COMM_WORLD, kv, &got, &found) == MPI_SUCCESS);
    CHECK(found == 0);
    CHECK(MPI_Keyval_free(&kv) == MPI_SUCCESS);
  }

  /* ---- datatype attributes ---- */
  {
    MPI_Datatype t;
    CHECK(MPI_Type_contiguous(3, MPI_INT, &t) == MPI_SUCCESS);
    int kv = MPI_KEYVAL_INVALID;
    CHECK(MPI_Type_create_keyval(NULL, type_del_fn, &kv, NULL) ==
          MPI_SUCCESS);
    CHECK(MPI_Type_set_attr(t, kv, (void *)0xD00D) == MPI_SUCCESS);
    void *got = NULL;
    int found = 0;
    CHECK(MPI_Type_get_attr(t, kv, &got, &found) == MPI_SUCCESS);
    CHECK(found == 1 && got == (void *)0xD00D);
    CHECK(MPI_Type_free(&t) == MPI_SUCCESS); /* delete callback runs */
    CHECK(type_del_calls == 1);
    CHECK(MPI_Type_free_keyval(&kv) == MPI_SUCCESS);
  }

  /* ---- persistent send modes (0 <-> 1) ---- */
  if (rank < 2) {
    int peer = 1 - rank;
    MPI_Comm pair;
    CHECK(MPI_Comm_split(MPI_COMM_WORLD, 0, rank, &pair) == MPI_SUCCESS);
    int sbuf = 60 + rank, rbuf = -1;
    MPI_Request sreq, rreq;
    CHECK(MPI_Ssend_init(&sbuf, 1, MPI_INT, 1 - rank, 3, pair, &sreq) ==
          MPI_SUCCESS);
    CHECK(MPI_Recv_init(&rbuf, 1, MPI_INT, 1 - rank, 3, pair, &rreq) ==
          MPI_SUCCESS);
    for (int round = 0; round < 3; round++) {
      rbuf = -1;
      sbuf = 60 + rank + round;
      CHECK(MPI_Start(&rreq) == MPI_SUCCESS);
      CHECK(MPI_Barrier(pair) == MPI_SUCCESS); /* recv posted first */
      CHECK(MPI_Start(&sreq) == MPI_SUCCESS);
      CHECK(MPI_Wait(&sreq, MPI_STATUS_IGNORE) == MPI_SUCCESS);
      CHECK(MPI_Wait(&rreq, MPI_STATUS_IGNORE) == MPI_SUCCESS);
      CHECK(rbuf == 60 + peer + round);
    }
    CHECK(MPI_Request_free(&sreq) == MPI_SUCCESS);
    CHECK(MPI_Request_free(&rreq) == MPI_SUCCESS);
    /* bsend/rsend persistent variants construct + fire once */
    MPI_Request breq;
    CHECK(MPI_Bsend_init(&sbuf, 1, MPI_INT, 1 - rank, 4, pair, &breq) ==
          MPI_SUCCESS);
    CHECK(MPI_Start(&breq) == MPI_SUCCESS);
    CHECK(MPI_Wait(&breq, MPI_STATUS_IGNORE) == MPI_SUCCESS);
    int dummy = -1;
    CHECK(MPI_Recv(&dummy, 1, MPI_INT, 1 - rank, 4, pair,
                   MPI_STATUS_IGNORE) == MPI_SUCCESS);
    CHECK(MPI_Request_free(&breq) == MPI_SUCCESS);
    MPI_Comm_free(&pair);
  } else {
    MPI_Comm dummy;
    CHECK(MPI_Comm_split(MPI_COMM_WORLD, 1, rank, &dummy) ==
          MPI_SUCCESS);
    MPI_Comm_free(&dummy);
  }

  /* ---- request-based RMA ---- */
  {
    long long cell = 0;
    MPI_Win win;
    CHECK(MPI_Win_create(&cell, sizeof cell, sizeof cell, MPI_INFO_NULL,
                         MPI_COMM_WORLD, &win) == MPI_SUCCESS);
    CHECK(MPI_Win_fence(0, win) == MPI_SUCCESS);
    long long one = 1;
    MPI_Request rr;
    CHECK(MPI_Raccumulate(&one, 1, MPI_LONG, 0, 0, 1, MPI_LONG, MPI_SUM,
                          win, &rr) == MPI_SUCCESS);
    CHECK(MPI_Wait(&rr, MPI_STATUS_IGNORE) == MPI_SUCCESS);
    CHECK(MPI_Win_fence(0, win) == MPI_SUCCESS);
    long long seen = -1;
    CHECK(MPI_Rget(&seen, 1, MPI_LONG, 0, 0, 1, MPI_LONG, win, &rr) ==
          MPI_SUCCESS);
    CHECK(MPI_Wait(&rr, MPI_STATUS_IGNORE) == MPI_SUCCESS);
    CHECK(seen == size);
    CHECK(MPI_Win_fence(0, win) == MPI_SUCCESS);
    CHECK(MPI_Win_free(&win) == MPI_SUCCESS);
  }

  /* ---- external32 canonical packing round-trip + wire check ---- */
  {
    int vals[3] = {0x01020304, 0x0A0B0C0D, -2};
    MPI_Aint psize = -1;
    CHECK(MPI_Pack_external_size("external32", 3, MPI_INT, &psize) ==
          MPI_SUCCESS && psize == 12);
    char buf[64];
    MPI_Aint pos = 0;
    CHECK(MPI_Pack_external("external32", vals, 3, MPI_INT, buf, 64,
                            &pos) == MPI_SUCCESS && pos == 12);
    /* canonical big-endian bytes */
    CHECK((unsigned char)buf[0] == 0x01 && (unsigned char)buf[3] == 0x04);
    int back[3] = {0, 0, 0};
    MPI_Aint rpos = 0;
    CHECK(MPI_Unpack_external("external32", buf, pos, &rpos, back, 3,
                              MPI_INT) == MPI_SUCCESS);
    CHECK(back[0] == vals[0] && back[2] == -2);
    CHECK(MPI_Pack_external("bogus", vals, 3, MPI_INT, buf, 64, &pos) ==
          MPI_ERR_ARG);

    /* homogeneous byte-sealed types swap at their element unit */
    MPI_Datatype hv;
    CHECK(MPI_Type_create_hvector(2, 1, 8, MPI_INT, &hv) ==
          MPI_SUCCESS);
    CHECK(MPI_Type_commit(&hv) == MPI_SUCCESS);
    int strided[4] = {0x11223344, -1, 0x55667788, -1};
    pos = 0;
    CHECK(MPI_Pack_external("external32", strided, 1, hv, buf, 64,
                            &pos) == MPI_SUCCESS && pos == 8);
    CHECK((unsigned char)buf[0] == 0x11 &&
          (unsigned char)buf[3] == 0x44);
    CHECK((unsigned char)buf[4] == 0x55);
    int sback[4] = {9, 9, 9, 9};
    rpos = 0;
    CHECK(MPI_Unpack_external("external32", buf, pos, &rpos, sback, 1,
                              hv) == MPI_SUCCESS);
    CHECK(sback[0] == 0x11223344 && sback[2] == 0x55667788);
    CHECK(sback[1] == 9); /* the gap is untouched */
    MPI_Type_free(&hv);

    /* element-sealed derived types (contiguous of ints) swap too */
    MPI_Datatype c3;
    CHECK(MPI_Type_contiguous(3, MPI_INT, &c3) == MPI_SUCCESS);
    CHECK(MPI_Type_commit(&c3) == MPI_SUCCESS);
    pos = 0;
    CHECK(MPI_Pack_external("external32", vals, 1, c3, buf, 64, &pos) ==
          MPI_SUCCESS && pos == 12);
    CHECK((unsigned char)buf[0] == 0x01 && (unsigned char)buf[3] == 0x04);
    int cback[3] = {0, 0, 0};
    rpos = 0;
    CHECK(MPI_Unpack_external("external32", buf, pos, &rpos, cback, 1,
                              c3) == MPI_SUCCESS);
    CHECK(cback[0] == vals[0] && cback[2] == vals[2]);
    MPI_Type_free(&c3);

    /* a mixed-field struct has no canonical element unit */
    {
      int bl[2] = {1, 1};
      MPI_Aint dp2[2] = {0, 4};
      MPI_Datatype ts[2] = {MPI_INT, MPI_DOUBLE}, mixed;
      CHECK(MPI_Type_create_struct(2, bl, dp2, ts, &mixed) ==
            MPI_SUCCESS);
      CHECK(MPI_Type_commit(&mixed) == MPI_SUCCESS);
      char mbuf[16];
      pos = 0;
      CHECK(MPI_Pack_external("external32", mbuf, 1, mixed, buf, 64,
                              &pos) == MPI_ERR_TYPE);
      MPI_Type_free(&mixed);
    }
  }

  /* ---- size-matched + f90 types ---- */
  {
    MPI_Datatype t;
    CHECK(MPI_Type_match_size(MPI_TYPECLASS_INTEGER, 8, &t) ==
          MPI_SUCCESS && t == MPI_LONG_LONG);
    CHECK(MPI_Type_match_size(MPI_TYPECLASS_REAL, 4, &t) ==
          MPI_SUCCESS && t == MPI_FLOAT);
    CHECK(MPI_Type_create_f90_integer(9, &t) == MPI_SUCCESS &&
          t == MPI_INT);
    CHECK(MPI_Type_create_f90_real(15, 300, &t) == MPI_SUCCESS &&
          t == MPI_DOUBLE);
    MPI_Datatype cx;
    CHECK(MPI_Type_create_f90_complex(6, 30, &cx) == MPI_SUCCESS);
    int sz = -1;
    CHECK(MPI_Type_size(cx, &sz) == MPI_SUCCESS && sz == 8);
    MPI_Type_free(&cx);
  }

  /* ---- MINLOC/MAXLOC over pair types ---- */
  {
    struct { double v; int i; } din, dout;
    din.v = (rank == 1) ? -3.5 : rank * 2.0 + 1.0; /* rank 1 wins min */
    din.i = rank;
    CHECK(MPI_Allreduce(&din, &dout, 1, MPI_DOUBLE_INT, MPI_MINLOC,
                        MPI_COMM_WORLD) == MPI_SUCCESS);
    CHECK(dout.v == -3.5 && dout.i == 1);
    struct { int v; int i; } iin, iout;
    iin.v = 100; /* all tie: MAXLOC takes the LOWEST index */
    iin.i = rank;
    CHECK(MPI_Allreduce(&iin, &iout, 1, MPI_2INT, MPI_MAXLOC,
                        MPI_COMM_WORLD) == MPI_SUCCESS);
    CHECK(iout.v == 100 && iout.i == 0);
    int cf = -1;
    CHECK(MPI_Op_commutative(MPI_MINLOC, &cf) == MPI_SUCCESS &&
          cf == 1);
    /* get_elements counts BASIC elements (2 per record) and the
     * set/get round-trip is exact, odd counts included */
    {
      MPI_Status est;
      memset(&est, 0, sizeof est);
      CHECK(MPI_Status_set_elements(&est, MPI_DOUBLE_INT, 3) ==
            MPI_SUCCESS);
      int ne = -1;
      CHECK(MPI_Get_elements(&est, MPI_DOUBLE_INT, &ne) ==
            MPI_SUCCESS && ne == 3);
      CHECK(MPI_Status_set_elements(&est, MPI_DOUBLE_INT, 4) ==
            MPI_SUCCESS);
      CHECK(MPI_Get_elements(&est, MPI_DOUBLE_INT, &ne) ==
            MPI_SUCCESS && ne == 4);
      /* a RECEIVED record still reports 2 basics */
      est._count = 16; /* one wire record */
      CHECK(MPI_Get_elements(&est, MPI_DOUBLE_INT, &ne) ==
            MPI_SUCCESS && ne == 2);
    }
    /* typemap size vs padded extent (type_size.c: 12 vs 16) */
    int psz = -1;
    long plb = -1, pext = -1;
    CHECK(MPI_Type_size(MPI_DOUBLE_INT, &psz) == MPI_SUCCESS &&
          psz == 12);
    CHECK(MPI_Type_get_extent(MPI_DOUBLE_INT, &plb, &pext) ==
          MPI_SUCCESS && pext == 16);
    /* pair types have no canonical external32 order */
    char pbuf[64];
    MPI_Aint ppos = 0, pes = -1;
    CHECK(MPI_Pack_external("external32", &din, 1, MPI_DOUBLE_INT, pbuf,
                            64, &ppos) == MPI_ERR_TYPE);
    CHECK(MPI_Pack_external_size("external32", 1, MPI_DOUBLE_INT,
                                 &pes) == MPI_ERR_TYPE);
    /* loc ops demand a pair type */
    double plain = 1.0, pout = 0.0;
    CHECK(MPI_Reduce_local(&plain, &pout, 1, MPI_DOUBLE, MPI_MINLOC) ==
          MPI_ERR_TYPE);
  }

  /* ---- generalized requests ---- */
  {
    int state = 0;
    MPI_Request gr;
    CHECK(MPI_Grequest_start(gq_query, gq_free, gq_cancel, &state,
                             &gr) == MPI_SUCCESS);
    int flag = -1;
    CHECK(MPI_Test(&gr, &flag, MPI_STATUS_IGNORE) == MPI_SUCCESS &&
          flag == 0);
    CHECK(MPI_Grequest_complete(gr) == MPI_SUCCESS);
    MPI_Status st;
    memset(&st, 0, sizeof st);
    CHECK(MPI_Wait(&gr, &st) == MPI_SUCCESS);
    CHECK(st._count == 42);      /* query_fn shaped the status */
    CHECK(state == 101);         /* query (+1) then free (+100) ran */
  }

  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("misc2_c OK on %d ranks\n", size);
  MPI_Finalize();
  return 0;
}
