"""OSU harness smoke tests: each sweep flavor produces sane rows on tiny
ladders (the perf harness itself must not rot)."""

import numpy as np

from benchmarks import osu_zmpi


def _check(rows, op):
    assert rows, "no rows"
    for r in rows:
        assert r["op"] == op
        assert r["bytes"] > 0
        assert r["latency_us"] > 0
        assert np.isfinite(r["bandwidth_MBps"])


def test_pt2pt_rows():
    _check(osu_zmpi.bench_pt2pt(max_size=64, iters=3), "pt2pt_pingpong")


def test_tcp_rows():
    _check(osu_zmpi.bench_tcp(max_size=64, iters=3), "tcp_pingpong")


def test_sizes_ladder():
    s = osu_zmpi._sizes(4096)
    assert s[0] == 4 and s[-1] == 4096
    assert all(b == a * 4 for a, b in zip(s, s[1:]))
