"""Datatype engine tests.

Pure-host pack/unpack without any network, modeled on the reference's
test/datatype suite (ddt_test.c, ddt_pack.c, position.c, unpack_ooo.c) —
SURVEY.md §4.
"""

import numpy as np
import pytest

import zhpe_ompi_tpu.datatype as dt
from zhpe_ompi_tpu.core import errors


class TestPredefined:
    def test_basic_sizes(self):
        assert dt.FLOAT.size == 4 and dt.FLOAT.extent == 4
        assert dt.DOUBLE.size == 8
        assert dt.BYTE.size == 1
        assert dt.BFLOAT16.size == 2  # TPU-first: bfloat16 is predefined

    def test_pair_type(self):
        assert dt.FLOAT_INT.size == 8
        tm = dt.FLOAT_INT.typemap()
        assert tm[0][1] == 0 and tm[1][1] == 4

    def test_from_np(self):
        assert dt.from_np_dtype(np.float32) is dt.FLOAT
        assert dt.from_np_dtype("bfloat16") is dt.BFLOAT16


class TestConstructors:
    def test_contiguous(self):
        t = dt.create_contiguous(4, dt.FLOAT).commit()
        assert t.size == 16 and t.extent == 16
        assert t.is_contiguous

    def test_vector_gaps(self):
        # 3 blocks of 2 floats, stride 4 floats: |XX..XX..XX|
        t = dt.create_vector(3, 2, 4, dt.FLOAT)
        assert t.size == 24
        assert not t.is_contiguous
        assert t.segments() == [(0, 8), (16, 8), (32, 8)]

    def test_vector_contig_when_stride_equals_blocklen(self):
        t = dt.create_vector(3, 2, 2, dt.FLOAT)
        assert t.is_contiguous

    def test_indexed(self):
        t = dt.create_indexed([2, 1], [0, 3], dt.INT)
        assert t.size == 12
        assert t.segments() == [(0, 8), (12, 4)]

    def test_struct(self):
        t = dt.create_struct([1, 1], [0, 8], [dt.INT, dt.DOUBLE])
        assert t.size == 12
        assert t.extent == 16
        assert t.homogeneous_dtype is None

    def test_subarray(self):
        # 4x4 array, take the middle 2x2 at (1,1)
        t = dt.create_subarray([4, 4], [2, 2], [1, 1], dt.FLOAT)
        assert t.size == 16
        assert t.extent == 64  # full array, per the standard
        assert t.segments() == [(20, 8), (36, 8)]

    def test_resized(self):
        t = dt.create_resized(dt.FLOAT, 0, 16)
        assert t.size == 4 and t.extent == 16

    def test_bounds_check(self):
        with pytest.raises(errors.ArgError):
            dt.create_subarray([4], [3], [2], dt.FLOAT)

    def test_zero_blocklength_vector(self):
        t = dt.create_vector(2, 0, 1, dt.INT)
        assert t.size == 0 and t.extent == 0
        assert dt.convertor.pack(np.zeros(4, np.int32), t, 2).nbytes == 0

    def test_positive_lb_indexed(self):
        # MPI: indexed([1],[1],INT) has lb=4, extent=4; element k's payload
        # sits at byte 4k+4
        t = dt.create_indexed([1], [1], dt.INT).commit()
        assert t.lb == 4 and t.extent == 4
        idx = dt.convertor.byte_index_map(t, 3)
        np.testing.assert_array_equal(idx, np.arange(4, 16))
        src = np.arange(8, dtype=np.int32)
        packed = dt.convertor.pack(src, t, 3)
        np.testing.assert_array_equal(packed.view(np.int32), [1, 2, 3])

    def test_negative_displacement_rejected(self):
        t = dt.create_hvector(2, 1, -4, dt.INT)
        with pytest.raises(errors.ArgError):
            dt.convertor.pack(np.zeros(4, np.int32), t, 1)


class TestPackUnpack:
    def test_contiguous_roundtrip(self):
        src = np.arange(16, dtype=np.float32)
        t = dt.create_contiguous(4, dt.FLOAT).commit()
        packed = dt.convertor.pack(src, t, 4)
        assert packed.nbytes == 64
        out = dt.convertor.unpack(packed, t, 4)
        np.testing.assert_array_equal(out.view(np.float32), src)

    def test_vector_pack(self):
        # matrix column extraction: 4x4 f32, column 1
        m = np.arange(16, dtype=np.float32).reshape(4, 4)
        col = dt.create_vector(4, 1, 4, dt.FLOAT).commit()
        packed = dt.convertor.pack(np.ascontiguousarray(m.ravel()[1:]), col, 1)
        np.testing.assert_array_equal(
            packed.view(np.float32), np.array([1, 5, 9, 13], dtype=np.float32)
        )

    def test_vector_unpack_roundtrip(self):
        src = np.arange(24, dtype=np.float32)
        t = dt.create_vector(3, 2, 4, dt.FLOAT).commit()
        count = 2
        packed = dt.convertor.pack(src, t, count)
        assert packed.nbytes == t.size * count
        dest = np.zeros_like(src)
        dt.convertor.unpack(packed, t, count, out=dest)
        idx = dt.convertor.byte_index_map(t, count)
        src_b = src.view(np.uint8)
        dest_b = dest.view(np.uint8)
        np.testing.assert_array_equal(dest_b[idx], src_b[idx])

    def test_struct_roundtrip(self):
        t = dt.create_struct([1, 2], [0, 8], [dt.INT, dt.DOUBLE]).commit()
        n = dt.convertor.span_bytes(t, 3)
        src = np.random.default_rng(0).integers(0, 255, n, dtype=np.uint8)
        packed = dt.convertor.pack(src, t, 3)
        assert packed.nbytes == t.size * 3
        dest = np.zeros(n, dtype=np.uint8)
        dt.convertor.unpack(packed, t, 3, out=dest)
        idx = dt.convertor.byte_index_map(t, 3)
        np.testing.assert_array_equal(dest[idx], src[idx])

    def test_truncation_raises(self):
        t = dt.create_contiguous(4, dt.FLOAT)
        with pytest.raises(errors.TruncateError):
            dt.convertor.pack(np.zeros(2, np.float32), t, 4)

    def test_position_partial_pack(self):
        """Resumable packing at arbitrary byte positions (position.c model)."""
        src = np.arange(40, dtype=np.float32)
        t = dt.create_vector(5, 1, 2, dt.FLOAT).commit()
        full = dt.convertor.pack(src, t, 2)
        chunks, pos = [], 0
        while pos < full.nbytes:
            chunk, pos = dt.convertor.pack_partial(src, t, 2, pos, 7)  # odd size
            chunks.append(chunk)
        np.testing.assert_array_equal(np.concatenate(chunks), full)

    def test_unpack_out_of_order(self):
        """Chunks landing out of order (unpack_ooo.c model)."""
        src = np.arange(40, dtype=np.float32)
        t = dt.create_vector(5, 1, 2, dt.FLOAT).commit()
        full = dt.convertor.pack(src, t, 2)
        dest = np.zeros_like(src)
        # split packed stream into 3 chunks, apply in reverse order
        bounds = [0, 13, 27, full.nbytes]
        for i in (2, 1, 0):
            chunk = full[bounds[i] : bounds[i + 1]]
            dt.convertor.unpack_partial(chunk, dest, t, 2, bounds[i])
        idx = dt.convertor.byte_index_map(t, 2)
        np.testing.assert_array_equal(
            dest.view(np.uint8)[idx], src.view(np.uint8)[idx]
        )


class TestDevicePath:
    def test_device_pack_gather(self):
        import jax.numpy as jnp

        x = jnp.arange(24, dtype=jnp.float32)
        t = dt.create_vector(3, 2, 4, dt.FLOAT).commit()
        packed = dt.convertor.device_pack(x, t, 2)
        host = dt.convertor.pack(np.asarray(x), t, 2).view(np.float32)
        np.testing.assert_array_equal(np.asarray(packed), host)

    def test_device_unpack_scatter(self):
        import jax.numpy as jnp

        t = dt.create_vector(3, 2, 4, dt.FLOAT).commit()
        packed = jnp.arange(12, dtype=jnp.float32)
        out = jnp.zeros(24, dtype=jnp.float32)
        res = dt.convertor.device_unpack(packed, t, 2, out)
        host = dt.convertor.unpack(np.asarray(packed), t, 2).view(np.float32)
        np.testing.assert_array_equal(np.asarray(res)[: host.shape[0]], host)

    def test_device_pack_jittable(self):
        import jax
        import jax.numpy as jnp

        t = dt.create_vector(3, 2, 4, dt.FLOAT).commit()
        f = jax.jit(lambda x: dt.convertor.device_pack(x, t, 2))
        x = jnp.arange(24, dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(f(x)), np.asarray(dt.convertor.device_pack(x, t, 2))
        )

    def test_bf16_device_pack(self):
        import jax.numpy as jnp

        x = jnp.arange(16, dtype=jnp.bfloat16)
        t = dt.create_vector(2, 2, 4, dt.BFLOAT16).commit()
        packed = dt.convertor.device_pack(x, t, 2)
        assert packed.dtype == jnp.bfloat16
        # vector(2,2,4) extent = ((2-1)*4+2) elements = 6, so the second
        # element of the type starts at element 6 (MPI extent semantics)
        np.testing.assert_array_equal(
            np.asarray(packed, dtype=np.float32),
            np.array([0, 1, 4, 5, 6, 7, 10, 11], dtype=np.float32),
        )


from zhpe_ompi_tpu.datatype import derived, predefined  # noqa: E402


class TestDarray:
    """MPI_Type_create_darray (ompi_datatype_create_darray.c): HPF-style
    block/cyclic decomposition — every rank's typemap must tile the
    global array exactly once across the comm."""

    def _coverage(self, size, gsizes, distribs, dargs, psizes, base):
        """Union of all ranks' byte offsets; asserts disjoint + complete."""
        import numpy as np
        from zhpe_ompi_tpu.datatype import convertor

        seen = []
        for r in range(size):
            dt = derived.create_darray(
                size, r, gsizes, distribs, dargs, psizes, base
            )
            seen.append(convertor.byte_index_map(dt, 1))
        allb = np.concatenate(seen)
        total = int(np.prod(gsizes)) * base.size
        assert allb.size == total
        assert np.array_equal(np.sort(allb), np.arange(total))
        return seen

    def test_block_2d(self):
        self._coverage(
            4, [4, 6], [derived.DISTRIBUTE_BLOCK] * 2, [-1, -1], [2, 2],
            predefined.FLOAT,
        )

    def test_cyclic_1d(self):
        import numpy as np

        seen = self._coverage(
            3, [10], [derived.DISTRIBUTE_CYCLIC], [-1], [3],
            predefined.DOUBLE,
        )
        # rank 0 owns global indices 0,3,6,9 under cyclic(1)
        idx = (np.asarray(seen[0]) // 8)[::8]
        assert list(idx) == [0, 3, 6, 9][: idx.size]

    def test_cyclic_block2_mixed_none(self):
        self._coverage(
            2, [8, 3],
            [derived.DISTRIBUTE_CYCLIC, derived.DISTRIBUTE_NONE],
            [2, -1], [2, 1], predefined.INT,
        )

    def test_pack_roundtrip(self):
        """Packing through a darray extracts exactly this rank's slice."""
        import numpy as np
        from zhpe_ompi_tpu.datatype import convertor

        g = np.arange(24, dtype=np.float32).reshape(4, 6)
        dt = derived.create_darray(
            2, 1, [4, 6], [derived.DISTRIBUTE_BLOCK,
                           derived.DISTRIBUTE_NONE],
            [-1, -1], [2, 1], predefined.FLOAT,
        )
        packed = convertor.pack(g, dt, 1)
        # rank 1 of a 2x1 BLOCK grid owns rows 2..3
        np.testing.assert_array_equal(
            np.frombuffer(packed, np.float32), g[2:].reshape(-1)
        )

    def test_grid_mismatch_raises(self):
        with pytest.raises(errors.ArgError):
            derived.create_darray(
                4, 0, [8], [derived.DISTRIBUTE_BLOCK], [-1], [3],
                predefined.FLOAT,
            )


def test_hindexed_block_matches_hindexed():
    from zhpe_ompi_tpu.datatype import (
        INT32_T,
        create_hindexed,
        create_hindexed_block,
    )
    from zhpe_ompi_tpu.datatype import convertor

    a = create_hindexed_block(2, [0, 24, 48], INT32_T)
    b = create_hindexed([2, 2, 2], [0, 24, 48], INT32_T)
    src = np.arange(20, dtype=np.int32)
    pa = convertor.pack(src, a, 1)
    pb = convertor.pack(src, b, 1)
    assert bytes(pa) == bytes(pb)
    assert a.size == 24 and a.extent == b.extent
