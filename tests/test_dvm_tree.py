"""Multi-host DVM tree tests — the routed half of the PRRTE analog
(``runtime/dvmtree.py`` + the tree plumbing grown into ``runtime/dvm.py``).

Three altitudes:

- **unit** (pure threads): tree planning, the routed store's
  cache/forward contract against a bare PMIx server.
- **thread-fast integration**: in-process daemon trees (``spawn_tree
  (in_process=True)``) hosting REAL rank subprocesses — launch routing,
  concurrent-launch admission, link-loss fault classification, elastic
  resize under an allreduce loop.  Daemons share this process's SPC
  space, so counter deltas aggregate across the tree.
- **slow real-process forms**: ``zprted --parent`` OS daemons — the
  kill-a-daemon drill (SIGKILL a leaf; its ranks die on the lifeline,
  survivors classify cause="daemon-tree", shrink, allreduce) and
  resize-under-traffic over a tree.
"""

import io
import os
import signal
import textwrap
import threading
import time

import pytest

from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.runtime import dvm as dvm_mod
from zhpe_ompi_tpu.runtime import dvmtree
from zhpe_ompi_tpu.runtime import pmix as pmix_mod
from zhpe_ompi_tpu.runtime import spc

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script(tmp_path, body: str, name: str = "prog.py") -> str:
    p = tmp_path / name
    p.write_text(
        "import sys\n"
        f"sys.path.insert(0, {_REPO!r})\n" + textwrap.dedent(body)
    )
    return str(p)


# --------------------------------------------------------------- planning


class TestTreePlan:
    def test_fanout2_binomialish(self):
        # daemon i's parent is (i-1)//2: 0 <- 1,2; 1 <- 3,4; 2 <- 5,6
        assert dvmtree.plan_tree(7, fanout=2) == \
            [None, 0, 0, 1, 1, 2, 2]

    def test_fanout1_chain(self):
        assert dvmtree.plan_tree(4, fanout=1) == [None, 0, 1, 2]

    def test_flat_star(self):
        assert dvmtree.plan_tree(5, fanout=0) == [None, 0, 0, 0, 0]

    def test_default_rides_mca_var(self):
        from zhpe_ompi_tpu.mca import var as mca_var

        mca_var.set_var("dvm_tree_fanout", 3)
        try:
            assert dvmtree.plan_tree(5) == [None, 0, 0, 0, 1]
        finally:
            mca_var.unset("dvm_tree_fanout")

    def test_block_placement_even(self):
        got = dvmtree.block_placement(list(range(6)), ["a", "b", "c"])
        assert got == {0: "a", 1: "a", 2: "b", 3: "b", 4: "c", 5: "c"}

    def test_block_placement_uneven(self):
        got = dvmtree.block_placement(list(range(4)), ["a", "b", "c"])
        # contiguous near-even blocks, earlier daemons fill first
        assert [got[r] for r in range(4)] == ["a", "a", "b", "c"]

    def test_block_placement_no_daemons_raises(self):
        with pytest.raises(errors.MpiError):
            dvmtree.block_placement([0, 1], [])


# ----------------------------------------------------------- routed store


class TestRoutedStore:
    """RoutedStore against a bare PmixServer: writes forward up, reads
    cache at the leaf, generation bumps invalidate."""

    def _pair(self):
        srv = pmix_mod.PmixServer()
        routed = dvmtree.RoutedStore(srv.address, timeout=10.0)
        return srv, routed

    def test_forward_writes_and_cache_reads(self):
        srv, routed = self._pair()
        try:
            f0 = spc.read("dvm_tree_forwards")
            h0 = spc.read("dvm_store_cache_hits")
            routed.ensure_ns("job", 1)
            routed.put("job", 0, "card:0", ["h", 1])
            routed.commit("job", 0)
            # first get: a miss that forwards up and caches
            assert routed.get("job", "card:0", timeout=5.0) == ["h", 1]
            hits_after_miss = spc.read("dvm_store_cache_hits") - h0
            # second get: leaf-served
            assert routed.get("job", "card:0", timeout=5.0) == ["h", 1]
            assert spc.read("dvm_store_cache_hits") - h0 == \
                hits_after_miss + 1
            assert spc.read("dvm_tree_forwards") > f0
            # the authoritative store saw the write
            assert srv.store.get("job", "card:0", timeout=1.0) == ["h", 1]
            assert routed.cached_keys() == ["job:card:0"]
        finally:
            routed.close()
            srv.close()
        assert dvmtree.stale_cache_state() == []

    def test_generation_bump_invalidates(self):
        srv, routed = self._pair()
        try:
            routed.ensure_ns("job", 1)
            routed.put("job", 0, "k", "old")
            routed.commit("job", 0)
            assert routed.get("job", "k", timeout=5.0) == "old"
            # the respawn-window shape: bump, then republish under the
            # fresh tag — the leaf cache must not serve the corpse's
            gen = srv.store.bump_generation("job")
            routed.invalidate_ns("job")  # the down-frame's effect
            srv.store.put("job", 0, "k", "new")
            srv.store.commit("job", 0)
            assert routed.get("job", "k", timeout=5.0,
                              min_generation=gen) == "new"
        finally:
            routed.close()
            srv.close()

    def test_min_generation_never_served_from_stale_cache(self):
        srv, routed = self._pair()
        try:
            routed.ensure_ns("job", 1)
            routed.put("job", 0, "k", "g0")
            routed.commit("job", 0)
            assert routed.get("job", "k", timeout=5.0) == "g0"  # cached
            srv.store.bump_generation("job")
            srv.store.put("job", 0, "k", "g1")
            srv.store.commit("job", 0)
            # WITHOUT the invalidation down-frame having arrived yet, a
            # min_generation get must still bypass the gen-0 cache entry
            value, gen = routed.get_meta("job", "k", timeout=5.0,
                                         min_generation=1)
            assert (value, gen) == ("g1", 1)
        finally:
            routed.close()
            srv.close()

    def test_lookup_never_cached(self):
        srv, routed = self._pair()
        try:
            routed.ensure_ns("job", 1)
            routed.put("job", -1, "resize:0", {"seq": 0})
            routed.commit("job", -1)
            assert list(routed.lookup("job", "resize:")) == ["resize:0"]
            srv.store.put("job", -1, "resize:1", {"seq": 1})
            srv.store.commit("job", -1)
            # the mutable keyspace: a second lookup sees the new key
            # immediately (no leaf cache in the way)
            assert sorted(routed.lookup("job", "resize:")) == \
                ["resize:0", "resize:1"]
            assert routed.cached_keys() == []
        finally:
            routed.close()
            srv.close()

    def test_single_flight_coalesces_first_readers(self):
        srv, routed = self._pair()
        try:
            routed.ensure_ns("job", 4)
            g0 = spc.read("pmix_gets")
            results = []

            def reader():
                results.append(routed.get("job", "late", timeout=10.0))

            threads = [threading.Thread(target=reader)
                       for _ in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.2)  # all four park on one in-flight fetch
            srv.store.put("job", 0, "late", 42)
            srv.store.commit("job", 0)
            for t in threads:
                t.join(timeout=10.0)
            assert results == [42, 42, 42, 42]
            # ONE upward fetch served the root store; the waiters hit
            # the leaf cache once it landed
            assert spc.read("pmix_gets") - g0 == 1
        finally:
            routed.close()
            srv.close()

    def test_close_drops_cache_and_clears_gate(self):
        srv, routed = self._pair()
        routed.ensure_ns("job", 1)
        routed.put("job", 0, "k", 1)
        routed.commit("job", 0)
        routed.get("job", "k", timeout=5.0)
        routed.close()
        srv.close()
        assert routed.cached_keys() == []
        assert dvmtree.stale_cache_state() == []
        with pytest.raises(errors.MpiError):
            routed.get("job", "k", timeout=0.5)


# ------------------------------------------------- in-process tree launch


class TestTreeLaunch:
    def _prog(self, tmp_path, n):
        return _script(tmp_path, f"""
            import zhpe_ompi_tpu as zmpi

            proc = zmpi.host_init()
            vals = proc.allgather(proc.rank + 1)
            assert vals == list(range(1, {n} + 1)), vals
            print(f"rank {{proc.rank}} OK")
            zmpi.host_finalize()
        """)

    def test_six_ranks_over_three_daemons(self, tmp_path):
        """A launch at the root places rank blocks across the tree;
        child-hosted ranks modex through THEIR daemon's routed store
        (cache hits + forwards move, the job computes correctly)."""
        tree = dvmtree.spawn_tree(3, fanout=2, in_process=True)
        try:
            assert [n["dvm"].tree_depth for n in tree.nodes] == [0, 1, 1]
            h0 = spc.read("dvm_store_cache_hits")
            f0 = spc.read("dvm_tree_forwards")
            cli = dvm_mod.DvmClient(tree.root_address)
            out, err = io.StringIO(), io.StringIO()
            rc = cli.launch(6, [self._prog(tmp_path, 6)], timeout=120.0,
                            stdout=out, stderr=err)
            assert rc == 0, (out.getvalue(), err.getvalue())
            assert out.getvalue().count("OK") == 6
            assert spc.read("dvm_store_cache_hits") > h0
            assert spc.read("dvm_tree_forwards") > f0
            # root placement knows all three daemons
            info = cli.treeinfo()
            assert info["root"] and len(info["daemons"]) == 3
            cli.close()
        finally:
            tree.stop()
        assert dvm_mod.live_dvms() == []
        assert dvmtree.stale_cache_state() == []

    def test_depth2_chain(self, tmp_path):
        """fanout=1 builds a root<-mid<-leaf chain: the leaf's store
        verbs are routed through the mid daemon's parent link, and a
        job spread over all three still computes."""
        tree = dvmtree.spawn_tree(3, fanout=1, in_process=True)
        try:
            assert [n["dvm"].tree_depth for n in tree.nodes] == [0, 1, 2]
            cli = dvm_mod.DvmClient(tree.root_address)
            out = io.StringIO()
            rc = cli.launch(3, [self._prog(tmp_path, 3)], timeout=120.0,
                            stdout=out, stderr=io.StringIO())
            assert rc == 0, out.getvalue()
            assert out.getvalue().count("OK") == 3
            cli.close()
        finally:
            tree.stop()

    def test_launch_must_target_root(self, tmp_path):
        tree = dvmtree.spawn_tree(2, in_process=True)
        try:
            child = dvm_mod.DvmClient(tree.addresses()[1])
            with pytest.raises(errors.MpiError,
                               match="must target the ROOT"):
                child.launch(1, [self._prog(tmp_path, 1)], timeout=30.0,
                             stdout=io.StringIO(),
                             stderr=io.StringIO())
            child.close()
        finally:
            tree.stop()

    def test_relayed_rpcs_reach_root_from_child(self):
        """stat/treeinfo against a CHILD daemon: treeinfo answers
        locally (depth 1, not root), stat relays to the root's
        authoritative view."""
        tree = dvmtree.spawn_tree(2, in_process=True)
        try:
            child = dvm_mod.DvmClient(tree.addresses()[1])
            info = child.treeinfo()
            assert info["depth"] == 1 and not info["root"]
            stat = child.stat()  # relayed: the root's job table
            assert stat["jobs"] == {}
            assert len(stat["daemons"]) == 2
            child.close()
        finally:
            tree.stop()

    def test_detached_daemon_leaves_placement(self, tmp_path):
        """An orderly child stop() relays up as daemon-detached: the
        root unlearns the subtree (at ANY depth — the leaf of a chain
        relays through the mid daemon), so the next launch never
        places ranks on a stopped daemon and wedges."""
        tree = dvmtree.spawn_tree(3, fanout=1, in_process=True)
        try:
            cli = dvm_mod.DvmClient(tree.root_address)
            assert len(cli.treeinfo()["daemons"]) == 3
            tree.nodes[2]["dvm"].stop()  # the depth-2 leaf, orderly
            deadline = time.monotonic() + 10.0
            while len(cli.treeinfo()["daemons"]) != 2:
                assert time.monotonic() < deadline, cli.treeinfo()
                time.sleep(0.05)
            out = io.StringIO()
            rc = cli.launch(4, [self._prog(tmp_path, 4)], timeout=120.0,
                            stdout=out, stderr=io.StringIO())
            assert rc == 0, out.getvalue()
            assert out.getvalue().count("OK") == 4
            cli.close()
        finally:
            tree.stop()

    def test_elastic_rejects_non_python(self):
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            with pytest.raises(errors.MpiError, match="Python-only"):
                cli.launch(1, ["/bin/true"], ft=True, max_size=2,
                           timeout=30.0, stdout=io.StringIO(),
                           stderr=io.StringIO())
            cli.close()
        finally:
            d.stop()

    def test_concurrent_launches_one_daemon(self, tmp_path):
        """The admission-serialization regression (the launch RPC once
        assumed ONE caller): two simultaneous launches into one daemon
        must not interleave job setup — distinct job ids, both jobs
        complete, both outputs whole."""
        progs = [
            _script(tmp_path, f"""
                import zhpe_ompi_tpu as zmpi

                proc = zmpi.host_init()
                vals = proc.allgather(proc.rank)
                assert vals == [0, 1], vals
                print(f"J{i} rank {{proc.rank}} OK")
                zmpi.host_finalize()
            """, name=f"prog{i}.py")
            for i in range(2)
        ]
        d = dvm_mod.Dvm()
        try:
            results: dict[int, tuple] = {}
            barrier = threading.Barrier(2)

            def one(i):
                cli = dvm_mod.DvmClient(d.address)
                out, err = io.StringIO(), io.StringIO()
                barrier.wait(timeout=10.0)
                rc = cli.launch(2, [progs[i]], timeout=120.0,
                                stdout=out, stderr=err)
                results[i] = (rc, cli.last_job_id, out.getvalue(),
                              err.getvalue())
                cli.close()

            threads = [threading.Thread(target=one, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=150.0)
            assert sorted(results) == [0, 1], results
            rcs = [results[i][0] for i in range(2)]
            ids = [results[i][1] for i in range(2)]
            assert rcs == [0, 0], results
            assert len(set(ids)) == 2, ids
            for i in range(2):
                assert results[i][2].count(f"J{i} rank") == 2, results[i]
        finally:
            d.stop()
        assert pmix_mod.stale_namespaces() == []


# --------------------------------------------------- fault routing (fast)


_FAULT_PROG = """
import os
import time

import numpy as np

import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu import ops
from zhpe_ompi_tpu.runtime import pmix as pmix_mod

victims = set(int(r) for r in sys.argv[1].split(","))
proc = zmpi.host_init()
proc.barrier()
print(f"READY rank={proc.rank}", flush=True)
if proc.rank in victims:
    # a victim rank idles until its daemon's death takes it (the
    # lifeline) or the test tears the tree down
    time.sleep(120.0)
    raise SystemExit(0)
deadline = time.monotonic() + 30.0
while time.monotonic() < deadline:
    if all(proc.ft_state.is_failed(v) for v in victims):
        break
    time.sleep(0.01)
else:
    print(f"TIMEOUT rank={proc.rank} failed="
          f"{sorted(proc.ft_state.failed())}", flush=True)
    raise SystemExit(1)
ts = time.time()
causes = sorted(set(proc.ft_state.cause_of(v) for v in victims))
# the store must still serve through THIS host's surviving daemon
addr, ns = os.environ["ZMPI_PMIX"].rsplit("/", 1)
cli = pmix_mod.PmixClient(addr, timeout=10.0)
card = cli.get(ns, "card:0", timeout=10.0)
cli.close()
assert card, card
proc.failure_ack()
sh = proc.shrink()
total = float(np.asarray(sh.allreduce(np.float64(proc.rank), ops.SUM)))
print(f"SURVIVOR-OK rank={proc.rank} ts={ts:.3f} "
      f"causes={','.join(causes)} total={total}", flush=True)
zmpi.host_finalize()
"""


def _parse_survivors(text):
    import re

    return re.findall(
        r"SURVIVOR-OK rank=(\d+) ts=([\d.]+) causes=([\w,-]+) "
        r"total=([\d.-]+)", text)


class TestDaemonFaultThreadFast:
    def test_child_link_loss_classifies_subtree(self, tmp_path):
        """Severing a child's parent link WITHOUT a detach is a daemon
        death to the root: every rank the subtree hosted is marked
        failed (cause="daemon-tree"), the classification floods the
        surviving tree, survivors shrink and compute."""
        prog = _script(tmp_path, _FAULT_PROG)
        tree = dvmtree.spawn_tree(2, in_process=True)
        try:
            cli = dvm_mod.DvmClient(tree.root_address)
            out, err = io.StringIO(), io.StringIO()
            done = {}

            def run():
                done["rc"] = cli.launch(
                    4, [prog, "2,3"], ft=True, timeout=120.0,
                    mca=[("ft_detector_period", "2.0"),
                         ("ft_detector_timeout", "60.0")],
                    stdout=out, stderr=err)

            t = threading.Thread(target=run)
            t.start()
            deadline = time.monotonic() + 60.0
            while out.getvalue().count("READY") < 4:
                assert time.monotonic() < deadline, \
                    (out.getvalue(), err.getvalue())
                time.sleep(0.05)
            t_cut = time.time()
            # sever the link (no detach): the root must classify ranks
            # 2 and 3 — the child daemon's block — as daemon-tree dead
            tree.nodes[1]["dvm"]._parent_link.close()
            t.join(timeout=90.0)
            assert not t.is_alive(), "job never completed"
            text = out.getvalue()
            survivors = _parse_survivors(text)
            assert len(survivors) == 2, (text, err.getvalue())
            for rank, ts, causes, total in survivors:
                assert int(rank) in (0, 1)
                assert causes == "daemon-tree"
                assert float(ts) - t_cut < 2.0
                assert float(total) == 1.0  # 0 + 1
            # victims never exited 0: the job carries 128+SIGKILL
            assert done["rc"] == 137, done
            cli.close()
        finally:
            tree.stop()
        assert dvm_mod.live_dvms() == []
        assert dvmtree.stale_cache_state() == []


# ------------------------------------------------- elastic resize (fast)


_ELASTIC_PROG = """
import os
import time

import numpy as np

import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu import ops
from zhpe_ompi_tpu.ft import recovery

ep = zmpi.host_init()
ses = recovery.ElasticSession(ep)
deadline = time.monotonic() + float(os.environ.get("TEST_ELASTIC_S",
                                                   "30"))
stop_after = int(os.environ.get("TEST_ELASTIC_STOP_AFTER", "999"))
resizes = 0
while True:
    n = ses.live.size
    want_stop = 1.0 if (time.monotonic() > deadline
                        or resizes >= stop_after) else 0.0
    out = ses.live.allreduce(np.array([1.0, want_stop]), ops.SUM)
    assert np.isclose(out[0], n), (out, n)
    if out[1] > 0:
        break  # collective stop: every live rank saw the same sum
    act = ses.step()
    if act in ("retire", "halt"):
        print(f"RETIRE rank={ep.rank}", flush=True)
        break
    if act == "resized":
        resizes += 1
        print(f"RESIZED rank={ep.rank} live={ses.live.size}",
              flush=True)
ses.close()
zmpi.host_finalize()
"""


class TestElasticResize:
    def _run_elastic(self, tmp_path, daemon_addr, n, max_size,
                     resizes, run_s=30.0):
        """Launch the elastic worker, apply ``resizes`` (a list of new
        sizes) from a second client, return (rc, stdout, stderr)."""
        prog = _script(tmp_path, _ELASTIC_PROG)
        cli = dvm_mod.DvmClient(daemon_addr)
        out, err = io.StringIO(), io.StringIO()
        done = {}

        def run():
            done["rc"] = cli.launch(
                n, [prog], ft=True, max_size=max_size, timeout=180.0,
                mca=[("ft_detector_period", "2.0"),
                     ("ft_detector_timeout", "60.0")],
                stdout=out, stderr=err)

        t = threading.Thread(target=run)
        t.start()
        try:
            ctl = dvm_mod.DvmClient(daemon_addr)
            deadline = time.monotonic() + 60.0
            while not ctl.stat()["jobs"]:
                assert time.monotonic() < deadline, err.getvalue()
                time.sleep(0.1)
            job_id = next(iter(ctl.stat()["jobs"]))
            events = []
            live = n
            for new_n in resizes:
                # wait until the PREVIOUS membership is fully live
                deadline = time.monotonic() + 60.0
                while ctl.stat()["jobs"][job_id]["live"] != live:
                    assert time.monotonic() < deadline, \
                        (ctl.stat(), out.getvalue(), err.getvalue())
                    time.sleep(0.1)
                time.sleep(1.0)  # a few allreduce iterations in between
                events.append(ctl.resize(job_id, new_n, timeout=90.0))
                live = new_n
            ctl.close()
        finally:
            t.join(timeout=200.0)
        assert not t.is_alive(), "elastic job never completed"
        return done["rc"], out.getvalue(), err.getvalue(), events

    def test_grow_then_shrink_under_allreduce(self, tmp_path,
                                              monkeypatch):
        """The resize-under-traffic shape, thread-fast: 2 -> 4 -> 2
        while an allreduce loop runs; every generation's collectives
        stay correct (the worker asserts sum == live size)."""
        monkeypatch.setenv("TEST_ELASTIC_S", "60")
        monkeypatch.setenv("TEST_ELASTIC_STOP_AFTER", "2")
        r0 = spc.read("dvm_resizes")
        d = dvm_mod.Dvm()
        try:
            rc, out, err, events = self._run_elastic(
                tmp_path, d.address, n=2, max_size=4, resizes=[4, 2])
            assert rc == 0, (out, err)
            assert events[0]["grown"] == [2, 3]
            assert events[1]["retired"] == [2, 3]
            # ONE generation bump per grow window; shrink does not bump
            assert events[0]["generation"] == 1
            assert events[1]["generation"] == 1
            assert events[0]["seq"] == 0 and events[1]["seq"] == 1
            # every surviving rank applied both events; retired ranks
            # said an orderly goodbye
            assert out.count("RESIZED rank=0 live=4") == 1, out
            assert out.count("RESIZED rank=0 live=2") == 1, out
            assert out.count("RETIRE") == 2, out
            assert spc.read("dvm_resizes") - r0 == 2
        finally:
            d.stop()
        assert pmix_mod.stale_namespaces() == []

    def test_resize_validation(self, tmp_path):
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            with pytest.raises(errors.MpiError, match="unknown job"):
                cli.resize("job999", 2)
            # a non-ft launch may not be elastic at all
            with pytest.raises(errors.MpiError, match="ft=True"):
                cli.launch(1, ["x.py"], max_size=2, timeout=30.0,
                           stdout=io.StringIO(), stderr=io.StringIO())
            with pytest.raises(errors.MpiError, match="below n"):
                cli.launch(3, ["x.py"], ft=True, max_size=2,
                           timeout=30.0, stdout=io.StringIO(),
                           stderr=io.StringIO())
            cli.close()
        finally:
            d.stop()


# ----------------------------------------------------- C ranks over --dvm


_HAVE_GCC = __import__("shutil").which("g++") is not None


@pytest.mark.skipif(not _HAVE_GCC, reason="no C++ toolchain")
class TestCRankPmix:
    """native/zompi_mpi.cpp speaks the store verbs: C ranks modex
    through ZMPI_PMIX (no coordinator), so C and mixed C/Python jobs
    ride --dvm — including over a tree, where a child-hosted C rank's
    gets land in its daemon's leaf cache."""

    @pytest.fixture(scope="class")
    def ring_c(self, tmp_path_factory):
        import subprocess
        import sys

        binp = str(tmp_path_factory.mktemp("cbin") / "ring_c")
        subprocess.run(
            [sys.executable, "-m", "zhpe_ompi_tpu.tools.zmpicc",
             os.path.join(_REPO, "examples", "ring_c.c"), "-o", binp],
            check=True, capture_output=True, text=True, timeout=600,
        )
        return binp

    def test_c_ring_in_dvm(self, ring_c):
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            out, err = io.StringIO(), io.StringIO()
            rc = cli.launch(3, [ring_c], timeout=120.0, stdout=out,
                            stderr=err)
            assert rc == 0, (out.getvalue(), err.getvalue())
            assert out.getvalue().count("OK") == 3
            cli.close()
        finally:
            d.stop()

    def test_c_ring_over_tree_hits_leaf_cache(self, ring_c):
        tree = dvmtree.spawn_tree(3, fanout=2, in_process=True)
        try:
            h0 = spc.read("dvm_store_cache_hits")
            cli = dvm_mod.DvmClient(tree.root_address)
            out, err = io.StringIO(), io.StringIO()
            rc = cli.launch(6, [ring_c], timeout=120.0, stdout=out,
                            stderr=err)
            assert rc == 0, (out.getvalue(), err.getvalue())
            assert out.getvalue().count("OK") == 6
            assert spc.read("dvm_store_cache_hits") > h0
            cli.close()
        finally:
            tree.stop()

    def test_mixed_mpmd_c_and_python(self, ring_c, tmp_path):
        """One WORLD, two app contexts (C + Python), one store-served
        wire-up: the Python block allgathers among itself while the C
        block rings among the full WORLD?  No — no cross-context
        traffic here: each context computes within its own ranks, both
        exit 0 (the launch/modex interop is what's under test)."""
        import subprocess
        import sys

        hello = str(tmp_path / "hello_c")
        subprocess.run(
            [sys.executable, "-m", "zhpe_ompi_tpu.tools.zmpicc",
             os.path.join(_REPO, "examples", "hello_c.c"), "-o", hello],
            check=True, capture_output=True, text=True, timeout=600,
        )
        prog = _script(tmp_path, """
            import zhpe_ompi_tpu as zmpi

            proc = zmpi.host_init()
            assert proc.size == 4
            print(f"py rank {proc.rank} OK")
            zmpi.host_finalize()
        """)
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            out, err = io.StringIO(), io.StringIO()
            rc = cli.launch(0, apps=[(2, [hello]), (2, [prog])],
                            timeout=120.0, stdout=out, stderr=err)
            assert rc == 0, (out.getvalue(), err.getvalue())
            text = out.getvalue()
            assert text.count("Hello, world") == 2
            assert text.count("py rank") == 2
            cli.close()
        finally:
            d.stop()


# ------------------------------------------------- real-process drills


@pytest.mark.slow
class TestKillADaemonDrill:
    """The acceptance drill over REAL processes: a 3-daemon tree hosts
    a 6-rank ft job (2 ranks per daemon); SIGKILL of a leaf daemon
    must (a) kill its two ranks through the lifeline, (b) classify
    exactly those ranks (cause="daemon-tree") on every survivor in
    < 2 s, (c) leave the surviving tree serving store traffic, and
    (d) let survivors shrink and allreduce correctly."""

    def test_sigkill_leaf_daemon(self, tmp_path):
        prog = _script(tmp_path, _FAULT_PROG)
        tree = dvmtree.spawn_tree(3, fanout=2, in_process=False)
        try:
            # block placement of 6 ranks over [root, d1, d2]: the leaf
            # daemon d2 hosts ranks 4 and 5
            cli = dvm_mod.DvmClient(tree.root_address)
            out, err = io.StringIO(), io.StringIO()
            done = {}

            def run():
                done["rc"] = cli.launch(
                    6, [prog, "4,5"], ft=True, timeout=180.0,
                    mca=[("ft_detector_period", "2.0"),
                         ("ft_detector_timeout", "60.0")],
                    stdout=out, stderr=err)

            t = threading.Thread(target=run)
            t.start()
            deadline = time.monotonic() + 90.0
            while out.getvalue().count("READY") < 6:
                assert time.monotonic() < deadline, \
                    (out.getvalue(), err.getvalue())
                time.sleep(0.05)
            ctl = dvm_mod.DvmClient(tree.root_address)
            job_id = next(iter(ctl.stat()["jobs"]))
            victim_pids = {r: p for r, p in ctl.pids(job_id).items()
                           if r in (4, 5)}
            assert len(victim_pids) == 2
            t_kill = time.time()
            tree.kill_node(2, signal.SIGKILL)
            t.join(timeout=120.0)
            assert not t.is_alive(), "job never completed"
            text = out.getvalue()
            survivors = _parse_survivors(text)
            assert len(survivors) == 4, (text, err.getvalue())
            for rank, ts, causes, total in survivors:
                assert int(rank) in (0, 1, 2, 3)
                assert causes == "daemon-tree"
                # < 2 s from SIGKILL to classification on EVERY survivor
                assert float(ts) - t_kill < 2.0, (rank, ts, t_kill)
                assert float(total) == 6.0  # 0+1+2+3
            assert done["rc"] == 137, done
            # the lifeline took the dead daemon's ranks with it
            lifeline_deadline = time.monotonic() + 5.0
            while time.monotonic() < lifeline_deadline:
                if not any(os.path.exists(f"/proc/{p}")
                           for p in victim_pids.values()):
                    break
                time.sleep(0.1)
            orphans = [p for p in victim_pids.values()
                       if os.path.exists(f"/proc/{p}")]
            assert not orphans, f"victim ranks outlived their daemon: " \
                                f"{orphans}"
            ctl.close()
            cli.close()
        finally:
            tree.stop()
        assert dvm_mod.orphaned_daemon_processes() == []


@pytest.mark.slow
class TestResizeUnderTrafficReal:
    def test_grow_shrink_over_tree(self, tmp_path, monkeypatch):
        """Resize-under-traffic over REAL zprted processes: a 2-daemon
        tree hosts an elastic job that grows 4 -> 6 (new ranks placed
        round-robin across the tree, FT_JOINing the live window) and
        shrinks 6 -> 3, with the allreduce loop asserting correctness
        at every membership."""
        monkeypatch.setenv("TEST_ELASTIC_S", "90")
        monkeypatch.setenv("TEST_ELASTIC_STOP_AFTER", "2")
        prog = _script(tmp_path, _ELASTIC_PROG)
        tree = dvmtree.spawn_tree(2, in_process=False)
        try:
            cli = dvm_mod.DvmClient(tree.root_address)
            out, err = io.StringIO(), io.StringIO()
            done = {}

            def run():
                done["rc"] = cli.launch(
                    4, [prog], ft=True, max_size=6, timeout=240.0,
                    mca=[("ft_detector_period", "2.0"),
                         ("ft_detector_timeout", "60.0")],
                    stdout=out, stderr=err)

            t = threading.Thread(target=run)
            t.start()
            try:
                ctl = dvm_mod.DvmClient(tree.root_address)
                deadline = time.monotonic() + 90.0
                while not ctl.stat()["jobs"]:
                    assert time.monotonic() < deadline, err.getvalue()
                    time.sleep(0.1)
                job_id = next(iter(ctl.stat()["jobs"]))
                for new_n, await_live in ((6, 4), (3, 6)):
                    deadline = time.monotonic() + 90.0
                    while ctl.stat()["jobs"][job_id]["live"] != \
                            await_live:
                        assert time.monotonic() < deadline, \
                            (ctl.stat(), out.getvalue(),
                             err.getvalue())
                        time.sleep(0.1)
                    time.sleep(1.5)
                    ctl.resize(job_id, new_n, timeout=120.0)
                ctl.close()
            finally:
                t.join(timeout=300.0)
            assert not t.is_alive(), "elastic job never completed"
            assert done["rc"] == 0, (out.getvalue(), err.getvalue())
            text = out.getvalue()
            # survivors applied both events, the three retirees left
            # orderly
            assert text.count("RESIZED rank=0 live=6") == 1, text
            assert text.count("RESIZED rank=0 live=3") == 1, text
            assert text.count("RETIRE") == 3, text
            cli.close()
        finally:
            tree.stop()
        assert dvm_mod.orphaned_daemon_processes() == []
