"""zmpi-info — component/parameter/counter introspection CLI.

Re-design of ``ompi_info`` (``ompi/tools/ompi_info`` — SURVEY.md §2.6):
dumps the framework/component registry with priorities and availability, the
full MCA variable table with current values and their sources (the MPI_T
cvar surface), and the SPC performance counters (the pvar surface).

Usage::

    python -m zhpe_ompi_tpu.tools.info            # everything
    python -m zhpe_ompi_tpu.tools.info --components
    python -m zhpe_ompi_tpu.tools.info --params [prefix]
    python -m zhpe_ompi_tpu.tools.info --pvars
    python -m zhpe_ompi_tpu.tools.info --json
"""

from __future__ import annotations

import argparse
import json
import sys


def _load_everything():
    """Import all in-tree components so their frameworks/vars register
    (the analog of opening every MCA framework)."""
    from ..coll.framework import coll_framework

    coll_framework()
    from ..io.fbtl import fbtl_framework
    from ..io.fcoll import fcoll_framework
    from ..io.fs import fs_framework

    fs_framework()
    fbtl_framework()
    fcoll_framework()
    from ..shmem.spml import spml_framework

    spml_framework()
    from ..coll import host  # registers host_coll_* vars  # noqa: F401
    from ..pt2pt import tcp  # registers tcp_* vars  # noqa: F401
    from ..pt2pt import universe  # registers pt2pt vars  # noqa: F401
    from ..parallel import mesh  # registers rte vars  # noqa: F401
    from ..coll import monitoring  # registers monitoring vars  # noqa: F401
    from ..utils import memchecker  # registers memchecker vars  # noqa: F401
    from ..runtime import dvm  # registers dvm_* daemon vars  # noqa: F401
    from ..runtime import dvmtree  # registers tree/placement vars  # noqa: F401
    from .. import native

    native.load()  # registration happens inside load(), not at import


def gather(prefix: str | None = None) -> dict:
    _load_everything()
    from .. import __version__
    from ..mca import component as mca_component
    from ..mca import var as mca_var
    from ..runtime import spc

    from ..coll import tuned

    data = {
        "version": __version__,
        "package": "zhpe_ompi_tpu",
        "frameworks": mca_component.info(),
        "profiles": tuned.profiles(),
        "params": [
            {
                "name": v.name,
                "value": v.value,
                "source": v.source.name,
                "default": v.default,
                "type": v.type.__name__,
                "description": v.description,
            }
            for v in mca_var.registry.all_vars()
            if prefix is None or v.name.startswith(prefix)
        ],
        "pvars": spc.snapshot(),
    }
    return data


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(prog="zmpi-info", description=__doc__)
    p.add_argument("--components", action="store_true")
    p.add_argument("--params", nargs="?", const="", metavar="PREFIX")
    p.add_argument("--pvars", action="store_true")
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    show_all = not (args.components or args.params is not None or args.pvars)
    data = gather(args.params or None)

    if args.json:
        print(json.dumps(data, indent=2, default=str))
        return 0

    print(f"zhpe_ompi_tpu {data['version']}")
    if show_all or args.components:
        print("\n== Frameworks / components ==")
        for fw in data["frameworks"]:
            print(f"  {fw['framework']}: {fw['description']}")
            for c in fw["components"]:
                avail = "" if c["available"] else "  (unavailable)"
                print(
                    f"    {c['name']:<12} priority={c['priority']:<4} "
                    f"v{c['version']}{avail}"
                )
    if show_all or args.params is not None:
        print("\n== MCA parameters ==")
        for v in data["params"]:
            print(
                f"  {v['name']:<40} = {v['value']!r:<16} "
                f"[{v['source']}] {v['description']}"
            )
    if show_all or args.pvars:
        print("\n== Shipped decision profiles (coll_tuned_dynamic_rules) ==")
        for name, path in data["profiles"].items():
            print(f"  {name:<12} {path}")
        print("\n== Performance variables (SPC) ==")
        if not data["pvars"]:
            print("  (no counters recorded)")
        for k, val in sorted(data["pvars"].items()):
            print(f"  {k:<40} = {val}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
