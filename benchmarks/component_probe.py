"""Component-level timing of the headline config on the real chip.

Gotcha this probe exists to encode: on a TUNNELED device, fetching a
large output times the tunnel (~30 MB/s), not the chip — every timed
function is wrapped to reduce its output to ONE scalar inside jit, so
the forced host fetch is 4 bytes and the window bounds device work only.

Run from repo root: python benchmarks/component_probe.py
"""

import sys
import time

sys.path.insert(0, ".")

import numpy as np


def scalarize(fn):
    import jax
    import jax.numpy as jnp

    def wrapped(*args):
        out = fn(*args)
        leaves = jax.tree.leaves(out)
        return sum(jnp.sum(l).astype(jnp.float32) for l in leaves[:4])

    return jax.jit(wrapped)


def bench_fn(fn, *args, iters=20, warm=3):
    out = fn(*args)
    for _ in range(warm):
        out = fn(*args)
    float(out)
    times = []
    for _ in range(4):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        float(out)  # scalar fetch bounds the window
        times.append((time.perf_counter() - t0) / iters)
    return float(np.median(times[1:]))  # drop the boost window


def main():
    import jax
    import jax.numpy as jnp

    from zhpe_ompi_tpu.models import transformer as tfm

    cfg = tfm.Config(vocab=8192, d_model=1024, n_heads=16, d_ff=4096,
                     n_layers=4, seq=512, dtype=jnp.bfloat16)
    cfg_naive = tfm.Config(vocab=8192, d_model=1024, n_heads=16, d_ff=4096,
                           n_layers=4, seq=512, dtype=jnp.bfloat16,
                           flash=False)
    batch = 8
    r = np.random.default_rng(0)
    params = jax.device_put(tfm.init_params(cfg, jax.random.PRNGKey(0)))
    tok = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
    tgt = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))

    import os

    phase = os.environ.get("PROBE_PHASE", "1")
    rows = []
    if phase == "1":
        rows = [
            ("fwd_hidden flash", scalarize(
                lambda p, t: tfm.forward_hidden(p, t, cfg)), (params, tok)),
            ("loss fwd flash", scalarize(
                lambda p, a, b: tfm.loss_fn(p, a, b, cfg)),
             (params, tok, tgt)),
            ("grad flash", scalarize(jax.value_and_grad(
                lambda p, a, b: tfm.loss_fn(p, a, b, cfg))),
             (params, tok, tgt)),
        ]
    elif phase == "naive":
        rows = [
            ("fwd_hidden naive", scalarize(
                lambda p, t: tfm.forward_hidden(p, t, cfg_naive)),
             (params, tok)),
            ("grad naive", scalarize(jax.value_and_grad(
                lambda p, a, b: tfm.loss_fn(p, a, b, cfg_naive))),
             (params, tok, tgt)),
        ]
    for name, fn, args in rows:
        t = bench_fn(fn, *args)
        print(f"{name:20s}: {t*1e3:7.2f} ms", flush=True)

    if phase == "1":
        # SGD tail
        grads = jax.jit(jax.grad(
            lambda p, a, b: tfm.loss_fn(p, a, b, cfg)))(params, tok, tgt)

        def sgd(p, g):
            return jax.tree.map(
                lambda a, b: (a - 1e-2 * b).astype(a.dtype), p, g)

        t = bench_fn(scalarize(sgd), params, grads)
        print(f"{'sgd update':20s}: {t*1e3:7.2f} ms", flush=True)
    if phase != "2":
        return

    # pure-matmul ceiling at the model's shapes
    BT = batch * cfg.seq
    key = jax.random.PRNGKey(1)
    x0 = jax.random.normal(key, (BT, 1024), jnp.bfloat16)
    ws = {
        "wq": jax.random.normal(key, (1024, 3072), jnp.bfloat16),
        "wo": jax.random.normal(key, (1024, 1024), jnp.bfloat16),
        "w1": jax.random.normal(key, (1024, 4096), jnp.bfloat16),
        "w2": jax.random.normal(key, (4096, 1024), jnp.bfloat16),
        "emb": jax.random.normal(key, (1024, 8192), jnp.bfloat16),
    }

    def mm(x, w):
        for _ in range(cfg.n_layers):
            a = x @ w["wq"]
            b = a[:, :1024] @ w["wo"]
            c = x @ w["w1"]
            d = c @ w["w2"]
            x = (x + b + d) / 30.0
        return (x @ w["emb"]).astype(jnp.float32)

    fl = (cfg.n_layers * (BT * 1024 * 3072 + BT * 1024 * 1024
                          + BT * 1024 * 4096 + BT * 4096 * 1024)
          + BT * 1024 * 8192) * 2
    t = bench_fn(scalarize(mm), x0, ws)
    print(f"{'matmul-only fwd':20s}: {t*1e3:7.2f} ms "
          f"({fl/t/1e12:.0f} TFLOP/s attained)", flush=True)


if __name__ == "__main__":
    main()
