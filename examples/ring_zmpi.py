"""ring_c.c analog (reference: examples/ring_c.c:19-60): pass a message
around the ring, decrementing at rank 0 until it reaches zero.

The reference loops blocking send/recv per hop; the SPMD form expresses
one lap as a single shifted permute and the decrement loop as traced
control flow — the whole protocol compiles to one XLA program.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/ring_zmpi.py
"""

import jax.numpy as jnp
import numpy as np

import zhpe_ompi_tpu as zmpi


def main():
    comm = zmpi.init()
    n = comm.size
    start = 10

    def body(_):
        rank = comm.rank()

        def lap(state):
            msg, laps = state
            # one full lap: n hops around the ring
            for _hop in range(n):
                msg = comm.shift(msg, 1, wrap=True)
            # rank 0 decrements as the reference's rank 0 does
            msg = jnp.where(rank == 0, msg - 1, msg)
            # every rank sees the post-decrement value next lap; keep
            # ranks consistent by broadcasting rank 0's view
            msg = comm.bcast(msg, root=0)
            return msg, laps + 1

        import jax

        msg0 = jnp.asarray(float(start))
        msg, laps = jax.lax.while_loop(
            lambda s: s[0] > 0, lap, (msg0, jnp.asarray(0))
        )
        return jnp.stack([msg, laps.astype(jnp.float32)])

    out = np.asarray(comm.run(body, jnp.zeros((n, 1))))
    msg, laps = out.reshape(n, 2)[0]
    print(f"message reached {int(msg)} after {int(laps)} laps "
          f"({int(laps) * n} hops) over {n} ranks")
    assert int(msg) == 0 and int(laps) == start
    zmpi.finalize()


if __name__ == "__main__":
    main()
