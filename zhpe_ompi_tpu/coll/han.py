"""coll/han — hierarchical topology-aware host collectives.

Re-design of ``ompi/mca/coll/han`` (Luo et al., "HAN: a Hierarchical
AutotuNed Collective Communication Framework", IEEE Cluster 2020) for
the Python host plane: every collective splits into an **intra phase**
inside each locality group (same-host ranks, where the send seam rides
the ``pt2pt/sm.py`` mmap rings) and an **inter phase** among one leader
per group (where it rides the zero-copy wire), because a flat ring that
interleaves shared-memory and wire hops runs at the speed of its
slowest hop.  A 2-host × 4-rank flat ring allreduce pays 8 wire-priced
hops; the two-level schedule pays exactly the leader exchange.

Topology comes from :func:`zhpe_ompi_tpu.pt2pt.groups.locality_groups`
(the ``(boot_id, segment)`` modex cards); each phase runs the FLAT
algorithms of ``coll/host.py`` unchanged on a
:class:`~zhpe_ompi_tpu.pt2pt.groups.GroupView` sub-endpoint — the
coll-rides-the-PML layering, applied twice.  Algorithms:

- ``allreduce``  — intra reduce → leader allreduce → intra bcast; above
  ``host_coll_large_msg`` the leader exchange takes the split
  (reduce-scatter + allgather ring) schedule explicitly, the
  bandwidth-optimal inter-node shape.
- ``bcast``      — root→leader hop (when the root is not its group's
  leader) → leader bcast → intra bcast.
- ``reduce``     — intra reduce → leader reduce to the root's leader →
  leader→root hop.
- ``barrier``    — intra gather → leader allgather → intra release.
- ``allgather``  — intra gather → leader allgather (blocks travel with
  their global rank map) → intra bcast.
- ``reduce_scatter`` — intra blockwise reduce → leader alltoall of each
  group's blocks → per-block combine → intra scatter (the leader phase
  rides the aggregated han exchange below).
- ``alltoall``/``alltoallv`` — intra gather of each member's full
  rank-indexed send list → ONE aggregated leader exchange per host pair
  (pairwise below ``coll_han_alltoall_bruck_min`` leaders, Bruck
  store-and-forward at or above it) → intra scatter of the reassembled
  receive lists.  Every cross-host block crosses the wire exactly once
  in O(hosts²) or O(hosts·log hosts) messages instead of the flat
  path's O(ranks²) — the MoE expert-dispatch pattern
  (``models/moe.py``).

Selection (the coll_han_component decision, wired through
``coll/host.py``'s dispatch seam and ``coll/tuned.py``'s dynamic-rules
files): ``coll_han_enable`` = ``auto`` (on only when the topology has
>= 2 locality groups with >= 2 members each), ``on`` (forced; a
degenerate topology falls back to the flat algorithms LOUDLY via the
``han_flat_fallbacks`` counter), or ``off``.  A
``<op> <comm_size_min> <msg_bytes_min> han`` line in the
``coll_tuned_dynamic_rules`` file requests han per op/size exactly like
a forced enable.  Non-commutative reductions always route flat (group
combine order is not rank order — correctness outranks tuning, as in
``coll/tuned.py``).

FT coexistence: each phase delegates to the parent endpoint's
send/recv, so peer death classifies as the same typed ``ProcFailed``
the flat path raises, ``revoke(COLL_CID)`` poisons the phase windows
through the cid alias the views register, and a shrink produces a
fresh endpoint whose first han collective derives fresh locality
groups (the rebuild contract ``tests/test_ulfm.py`` exercises).
"""

from __future__ import annotations

import functools
from typing import Any

import numpy as np

from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from ..pt2pt import groups as groups_mod
from ..pt2pt.groups import LEADER_WINDOW, GroupView, payload_bytes
from ..runtime import flightrec
from ..runtime import spc
from ..runtime import ztrace
from . import host

_stream = mca_output.open_stream("coll_han")

# category derivation (tools/mpit.py): the hierarchical-collective
# plane's vars (coll_han_*) and counters (coll_han_*, han_*) are ONE
# family
mca_var.register_family("coll_han", "han")
mca_var.register_family("han", "han")


def _recorded(opname: str):
    """Flight-recorder enter/exit around a hierarchical collective —
    exit records only on SUCCESS, so a postmortem window shows the
    schedule a failing rank died inside (an aborted collective's
    missing exit is the signal, not a gap).  While the tracing plane
    is armed the same pairing records one COLL span per schedule (the
    same success-only discipline: an aborted collective's missing
    span is the signal)."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(ctx, *args, **kwargs):
            flightrec.record(flightrec.COLL_ENTER, op=opname)
            sp = ztrace.begin(ztrace.COLL, getattr(ctx, "rank", -1),
                              op=opname) if ztrace.active else None
            out = fn(ctx, *args, **kwargs)
            flightrec.record(flightrec.COLL_EXIT, op=opname)
            if sp is not None:
                sp.end()
            return out
        return wrapper
    return deco


mca_var.register(
    "coll_han_inter_segment", 1 << 20,
    "Segment size (bytes) of the large-message leader exchange: the "
    "inter phase processes the reduced payload in pieces of at most "
    "this size, so every leader-to-leader transfer stays on the eager "
    "zero-copy wire path (a monolithic half/chunk above "
    "tcp_eager_limit would fall into RTS/CTS rendezvous and pay its "
    "defensive copy + round trips).  Default matches tcp_eager_limit",
    type=int,
)
mca_var.register(
    "coll_han_numa_level", "auto",
    "Third (NUMA) topology level of the hierarchical collectives: "
    "nest an intra-DOMAIN phase under the host level — intra-domain "
    "reduce/bcast, an intra-host domain-leader exchange over the sm "
    "rings, and the inter-host wire exchange among host leaders.  "
    "auto = engage when some host has >= 2 domains of >= 2 members "
    "(the pynuma: modex tokens / sm_numa_id emulation); on = forced — "
    "a degenerate NUMA structure falls back to the TWO-LEVEL path "
    "loudly (han_numa_fallbacks), never silently and never all the "
    "way to flat while the host level is viable; off = two-level only",
    enum=("auto", "on", "off"),
)
mca_var.register(
    "coll_han_pipeline", "auto",
    "Pipelined inter/intra overlap of the segmented leader exchange "
    "(the reference han's 'w' variants): segment k's intra bcast is "
    "ISSUED nonblocking — the deferred-contract isend engine drains it "
    "onto the rings — while the leaders already run segment k+1's wire "
    "exchange.  auto/on = pipeline whenever the large-message "
    "segmented exchange yields >= 2 segments; off = the sequential "
    "schedule (every segment's exchange and bcast strictly ordered)",
    enum=("auto", "on", "off"),
)

#: collectives with a two-level schedule — canonical home is the
#: dispatch seam (coll/host.py), re-exported here for the decision API
HAN_OPS = host.HAN_OPS


class _Topology:
    """One endpoint's locality structure: ascending-member groups
    ordered by leader (min) rank; ``leaders[i]`` leads ``groups[i]``.
    ``nested`` (when known) adds the NUMA level: per host, its domain
    member-lists ordered by domain leader — the three-level schedule's
    input."""

    __slots__ = ("groups", "leaders", "gidx", "degenerate", "qualified",
                 "nested", "numa_viable", "numa_qualified")

    def __init__(self, size: int, rank: int, groups: list[list[int]],
                 nested: list[list[list[int]]] | None = None):
        flat = sorted(r for g in groups for r in g)
        if flat != list(range(size)):
            raise errors.ArgError(
                f"han groups must partition ranks 0..{size - 1}, got "
                f"{groups}"
            )
        self.groups = sorted((sorted(g) for g in groups),
                             key=lambda g: g[0])
        self.leaders = [g[0] for g in self.groups]
        self.gidx = next(i for i, g in enumerate(self.groups)
                         if rank in g)
        n = len(self.groups)
        # degenerate: one group (pure intra), all singletons (pure
        # inter == flat with extra dispatch), or more groups than tag
        # windows — nothing hierarchical to win
        self.degenerate = n < 2 or n == size or n > groups_mod.MAX_GROUPS
        # the auto-on bar: at least two groups that actually HAVE an
        # intra phase — anything less and flat is at least as good
        self.qualified = (not self.degenerate) and sum(
            1 for g in self.groups if len(g) >= 2) >= 2
        self.nested = None
        self.numa_viable = self.numa_qualified = False
        if nested is not None:
            norm = []
            for hostdoms in nested:
                norm.append(sorted((sorted(d) for d in hostdoms),
                                   key=lambda d: d[0]))
            norm.sort(key=lambda doms: doms[0][0])
            if [sorted(r for d in h for r in d) for h in norm] \
                    != self.groups:
                raise errors.ArgError(
                    "han nested domains must partition their host "
                    f"groups, got {nested} over {self.groups}"
                )
            self.nested = norm
            n_domains = sum(len(h) for h in norm)
            # viable: some host actually SPLITS into domains, and the
            # window partition can carry the layout (global domain
            # index + per-host dleader window + the wire window)
            self.numa_viable = (
                any(len(h) >= 2 for h in norm)
                and n_domains <= groups_mod.DOMAIN_WINDOWS
                and len(norm) <= groups_mod.MAX_HOSTS_NESTED
            )
            # the auto bar: >= 2 domains of >= 2 members on some host —
            # anything less and the two-level schedule is at least as
            # good (a lone multi-rank domain IS the host group)
            self.numa_qualified = self.numa_viable and any(
                sum(1 for d in h if len(d) >= 2) >= 2 for h in norm)

    def group_of(self, rank: int) -> int:
        return next(i for i, g in enumerate(self.groups) if rank in g)

    def domain_of(self, rank: int) -> tuple[int, int]:
        """(host index, domain index within host) of ``rank``."""
        h = self.group_of(rank)
        return h, next(i for i, d in enumerate(self.nested[h])
                       if rank in d)

    def domain_window(self, h: int, d: int) -> int:
        """Window id of domain ``d`` of host ``h``: the disjoint
        domain range (DOMAIN_WINDOW_BASE +) indexed globally in
        (host, domain) order."""
        return groups_mod.DOMAIN_WINDOW_BASE + \
            sum(len(self.nested[i]) for i in range(h)) + d


def topology(ctx, groups: list[list[int]] | None = None) -> _Topology:
    """The endpoint's (cached) locality topology; ``groups`` overrides
    the modex derivation (test harnesses emulating multi-host layouts
    on the thread plane) — depth-2 lists give host groups only,
    depth-3 lists (host → domain → members) emulate the NUMA level.
    Never raises out of a malformed FOREIGN card: the nested
    derivation counts it and demotes the rank to a singleton domain."""
    if groups is None:
        cached = getattr(ctx, "_han_topology", None)
        if cached is not None:
            return cached
        nested = groups_mod.locality_groups(ctx, nested=True)
        hostg = [sorted(r for d in h for r in d) for h in nested]
        topo = _Topology(ctx.size, ctx.rank, hostg, nested=nested)
        ctx._han_topology = topo
        return topo
    if groups and groups[0] and isinstance(groups[0][0], (list, tuple)):
        hostg = [[r for d in h for r in d] for h in groups]
        return _Topology(ctx.size, ctx.rank, hostg,
                         nested=[[list(d) for d in h] for h in groups])
    return _Topology(ctx.size, ctx.rank, groups)


def invalidate(ctx) -> None:
    """Drop the cached topology/views (a membership change: JOIN
    re-modex scrubbing a rejoiner's card).  The next han collective
    re-derives the groups — the same rebuild a shrink gets by being a
    fresh endpoint."""
    for attr in ("_han_topology", "_han_views"):
        try:
            delattr(ctx, attr)
        except AttributeError:
            pass


def _views(ctx, topo: _Topology) -> tuple[GroupView, GroupView | None]:
    """(intra view, leader view-or-None) for this rank, cached per
    group structure.  Building the views IS the leader election (the
    deterministic min-rank rule), counted in
    ``coll_han_leader_elections``."""
    cache = getattr(ctx, "_han_views", None)
    if cache is None:
        cache = {}
        ctx._han_views = cache
    key = tuple(tuple(g) for g in topo.groups)
    got = cache.get(key)
    if got is None:
        intra = GroupView(ctx, topo.groups[topo.gidx],
                          window=topo.gidx, plane="intra")
        inter = None
        if ctx.rank in topo.leaders:
            inter = GroupView(ctx, topo.leaders, window=LEADER_WINDOW,
                              plane="inter")
        spc.record("coll_han_leader_elections", 1)
        got = (intra, inter)
        cache[key] = got
    return got


def _numa_views(ctx, topo: _Topology
                ) -> tuple[GroupView, GroupView | None, GroupView | None]:
    """(intra-domain view, per-host domain-leader view or None, wire
    view or None) for this rank under the three-level schedule, cached
    per nested structure.  The domain and dleader views NEST inside the
    host view (view-of-view: members in host-view coordinates, traffic
    flattened onto the base endpoint under the nested view's OWN
    window), so the three-level layout exercises exactly the rel/parent
    translation machinery the nesting contract specifies."""
    cache = getattr(ctx, "_han_views", None)
    if cache is None:
        cache = {}
        ctx._han_views = cache
    key = ("numa",) + tuple(
        tuple(tuple(d) for d in h) for h in topo.nested)
    got = cache.get(key)
    if got is None:
        h = topo.gidx
        doms = topo.nested[h]
        hview = GroupView(ctx, topo.groups[h], window=h, plane="intra")
        _h, d = topo.domain_of(ctx.rank)
        dview = GroupView(
            hview, [hview.rel_base(r) for r in doms[d]],
            window=topo.domain_window(h, d), plane="intra")
        dlview = None
        dleaders = [dom[0] for dom in doms]
        if ctx.rank in dleaders:
            dlview = GroupView(
                hview, [hview.rel_base(r) for r in dleaders],
                window=groups_mod.HOST_LEADER_BASE + h, plane="dleader")
        wview = None
        if ctx.rank in topo.leaders:
            wview = GroupView(ctx, topo.leaders, window=LEADER_WINDOW,
                              plane="inter")
        spc.record("coll_han_leader_elections", 1)
        got = (dview, dlview, wview)
        cache[key] = got
    return got


def _flat_fallback(ctx, opname: str, reason: str) -> None:
    """An explicitly-requested han that cannot run hierarchically:
    LOUD degradation — counted (the OSU ladder gates on zero) and
    emitted, never silent."""
    spc.record("han_flat_fallbacks", 1)
    mca_output.emit(
        _stream,
        "rank %s: %s requested the hierarchical (han) path but %s; "
        "running the flat algorithm", getattr(ctx, "rank", "?"),
        opname, reason,
    )


def _numa_fallback(ctx, opname: str, reason: str) -> None:
    """A forced NUMA (three-level) schedule that cannot nest: LOUD
    degradation to the TWO-LEVEL path — counted and emitted.  Distinct
    from ``_flat_fallback`` by contract: while the host level is
    viable, a degenerate NUMA structure costs only the domain phase,
    never the whole hierarchy."""
    spc.record("han_numa_fallbacks", 1)
    mca_output.emit(
        _stream,
        "rank %s: %s requested the NUMA (three-level) schedule but %s; "
        "running the two-level path", getattr(ctx, "rank", "?"),
        opname, reason,
    )


#: collectives with a three-level (NUMA) schedule; the rest run their
#: two-level schedule even when the NUMA level is engaged (their phase
#: structure gains nothing from a third nesting — documented in README)
NUMA_OPS = frozenset(("allreduce", "bcast", "barrier"))


def _numa_mode() -> str:
    return str(mca_var.get("coll_han_numa_level", "auto"))


def _use_numa(ctx, topo: _Topology, opname: str) -> bool:
    """Per-collective decision for the third (NUMA) level, consulted
    AFTER han itself was selected.  Deterministic across ranks: it
    reads only the shared topology and MCA state."""
    mode = _numa_mode()
    if mode == "off" or topo.nested is None or opname not in NUMA_OPS:
        return False
    if mode == "on":
        if topo.numa_viable:
            return True
        _numa_fallback(
            ctx, opname,
            "the NUMA structure is degenerate "
            f"({sum(len(h) for h in topo.nested)} domain(s) over "
            f"{len(topo.groups)} host(s))")
        return False
    return topo.numa_qualified


def topology_key(ctx=None):
    """The job's ``(n_hosts, n_domains, ranks_per_domain)`` decision-
    table key: the ``coll_tuned_topology`` var when set, else derived
    from the endpoint's cached locality topology (``ranks_per_domain``
    is the LARGEST domain — tables for ragged layouts should pin the
    coarser fields and wildcard it).  Never raises (ZL008): no context
    or an underivable topology matches wildcard sections only."""
    from . import ztable

    key = ztable.job_topology_key()
    if key is not None or ctx is None:
        return key
    try:
        topo = topology(ctx)
    except errors.MpiError as e:
        mca_output.verbose(
            2, _stream,
            "topology-key derivation failed (%s); tuned tables match "
            "wildcard sections only", e,
        )
        return None
    n_hosts = len(topo.groups)
    if topo.nested:
        n_domains = sum(len(h) for h in topo.nested)
        biggest = max(
            (len(d) for h in topo.nested for d in h), default=1)
    else:
        n_domains = n_hosts
        biggest = max((len(g) for g in topo.groups), default=1)
    return (n_hosts, n_domains, biggest)


def _rule_requests_han(opname: str, size: int, payload: Any,
                       ctx=None) -> bool:
    # the table ladder (coll/ztable.py): store-served ztune table, then
    # the rules file — topology-keyed when a context can derive a key.
    # Size matching uses the LOCAL payload size — ops whose payloads
    # are not congruent across ranks (the host plane's bcast has none
    # at non-roots) must use msg_bytes_min 0.
    from . import ztable

    if not ztable.active():
        return False
    return ztable.resolve_rule(
        opname, size, payload_bytes(payload), topology_key(ctx)) == "han"


def wants_han(ctx, opname: str, payload: Any = None, op=None,
              mode: str | None = None) -> bool:
    """The han half of the host-plane decision (called from
    ``coll/host.py``'s dispatch seam): True when this collective should
    take the two-level schedule."""
    if mode is None:
        mode = str(mca_var.get("coll_han_enable", "auto"))
    if mode == "off" or opname not in HAN_OPS:
        return False
    if getattr(ctx, "_han_subview", False):
        return False  # phase traffic re-enters the flat algorithms
    requested = mode == "on" or _rule_requests_han(
        opname, getattr(ctx, "size", 0), payload, ctx)
    if not requested and mode != "auto":  # unknown mode string: off
        return False
    topo = topology(ctx)
    noncommutative = op is not None and not getattr(op, "commute", True)
    # the NUMA level can carry a host-degenerate topology (e.g. one
    # host whose domains split): the hierarchy then lives entirely in
    # the domain phase + dleader exchange
    numa_carries = (
        _numa_mode() != "off" and opname in NUMA_OPS
        and topo.numa_qualified
    )
    if requested:
        if noncommutative:
            _flat_fallback(ctx, opname, "the op is non-commutative "
                           "(group combine order != rank order)")
            return False
        if topo.degenerate:
            if numa_carries:
                return True
            _flat_fallback(ctx, opname, "the topology is degenerate "
                           f"({len(topo.groups)} locality group(s) over "
                           f"{ctx.size} rank(s))")
            return False
        return True
    return (topo.qualified or numa_carries) and not noncommutative


def _require_commutative(op, opname: str) -> None:
    if op is not None and not getattr(op, "commute", True):
        raise errors.ArgError(
            f"han {opname} requires a commutative op (group combine "
            "order is not rank order); use the flat path"
        )


# ------------------------------------------------------------ allreduce


def _pipeline_geometry(n_groups: int, value: Any
                       ) -> tuple[int, int] | None:
    """Segment geometry ``(seg_elems, nseg)`` of the pipelined leader
    exchange, derived from the ALLREDUCE INPUT — congruent on every
    rank by the MPI contract, so leaders and members reach the
    identical schedule with no negotiation (members never see the
    reduced array the sequential path sizes its segments from).  None
    when the segmented large-message path would not engage, or when it
    yields a single segment (nothing to overlap)."""
    large = int(mca_var.get("host_coll_large_msg", 256 * 1024))
    if (
        not isinstance(value, np.ndarray)
        or value.nbytes < large
        or value.size < n_groups
    ):
        return None
    seg_bytes = max(1, int(mca_var.get("coll_han_inter_segment",
                                       1 << 20)))
    seg = max(n_groups, seg_bytes // max(value.dtype.itemsize, 1))
    if value.size <= seg:
        return None
    return seg, -(-value.size // seg)


def _allreduce_pipelined(intra, inter, value: Any, op,
                         geom: tuple[int, int]) -> Any:
    """The reference han's "w" overlap: the segmented leader exchange
    isends segment k's intra bcast (nonblocking — the deferred-contract
    engine drains it onto the rings) while segment k+1's wire exchange
    already runs, so the intra plane and the wire stay busy at once
    instead of strictly alternating.  Members consume the segments
    SEQUENTIALLY with the blocking binomial phase — one intra-window
    tag bump per segment as each bcast runs, matching the leader's
    one-ibcast-per-segment issue order — so a member is forwarding
    segment k while its leader already exchanges k+1."""
    from ..pt2pt.requests import wait_all
    from . import nbc

    seg, nseg = geom
    spc.record("coll_han_pipelined", 1)
    partial = host.reduce(intra, value, op, root=0) \
        if intra.size > 1 else value
    pieces: list = [None] * nseg
    if inter is not None:
        flat = np.ascontiguousarray(partial).reshape(-1)
        breqs = []
        for k in range(nseg):
            piece = flat[k * seg:(k + 1) * seg]
            if inter.size > 2:
                tag = host._next_tag(inter, host.TAG_ALLREDUCE)
                done = host._allreduce_ring(inter, piece, op, tag)
            else:
                done = host.allreduce(inter, piece, op)
            pieces[k] = np.asarray(done).reshape(-1)
            if intra.size > 1:
                # the isends under this ibcast pin `pieces[k]` until
                # drained — freshly produced per segment, never mutated
                breqs.append(nbc.ibcast(intra, pieces[k], root=0))
        wait_all(breqs)
    else:
        # member: consume the per-segment bcasts with the BLOCKING
        # binomial phase — event-blocked receives; a polling
        # SchedRequest wait per segment measurably steals scheduler
        # quanta from the producing leader on small hosts.  Wire-
        # compatible with the leader's nonblocking issue: nbc.ibcast
        # and the flat binomial bcast run the identical tree and tag
        # sequence, so each side picks the form that fits its role.
        for k in range(nseg):
            pieces[k] = np.asarray(host.bcast(
                intra, None, root=0, algorithm="binomial")).reshape(-1)
    # nseg >= 2 by construction: _pipeline_geometry returns None for a
    # single-segment payload (nothing to overlap)
    return np.concatenate(pieces).reshape(np.asarray(value).shape)


def _allreduce_numa(ctx, topo: _Topology, value: Any, op) -> Any:
    """Three-level allreduce: intra-DOMAIN reduce → intra-host
    domain-leader reduce (over the sm rings, the dleader window) →
    inter-host wire exchange among host leaders (the same segmented
    reduce-scatter+allgather schedule as two-level) → dleader bcast →
    domain bcast.  Exactly the hops that cross the wire carry exactly
    one host-reduced payload — a domains-as-hosts layout pays the full
    leader exchange among every domain leader instead."""
    dview, dlview, wview = _numa_views(ctx, topo)
    spc.record("coll_han_numa_collectives", 1)
    rank = getattr(ctx, "rank", -1)
    flightrec.record(flightrec.COLL_ENTER, op="allreduce",
                     phase="domain", sched="han3")
    with ztrace.phase_span("intra-domain", rank, op="allreduce",
                           sched="han3"):
        part = host.reduce(dview, value, op, root=0) \
            if dview.size > 1 else value
    if dlview is not None:
        if dlview.size > 1:
            with ztrace.phase_span("dleader", rank, op="allreduce",
                                   sched="han3"):
                part = host.reduce(dlview, part, op, root=0)
        if wview is not None:
            part = _leader_allreduce(wview, part, op)
        if dlview.size > 1:
            with ztrace.phase_span("dleader", rank, op="allreduce",
                                   sched="han3"):
                part = host.bcast(dlview, part, root=0,
                                  algorithm="binomial")
    if dview.size > 1:
        with ztrace.phase_span("intra-domain", rank, op="allreduce",
                               sched="han3"):
            part = host.bcast(dview, part, root=0,
                              algorithm="binomial")
    flightrec.record(flightrec.COLL_EXIT, op="allreduce",
                     phase="domain", sched="han3")
    return part


@_recorded("allreduce")
def allreduce(ctx, value: Any, op,
              groups: list[list[int]] | None = None) -> Any:
    """Two-level allreduce: intra reduce → leader allreduce → intra
    bcast.  Above ``host_coll_large_msg`` the leader exchange runs the
    split (reduce-scatter + allgather) ring explicitly — the
    bandwidth-optimal inter-node schedule, applied to exactly the hops
    that cross the wire — and, with ``coll_han_pipeline`` auto/on and
    >= 2 segments, OVERLAPS each segment's intra bcast with the next
    segment's wire exchange (the "w" pipelining).  With the NUMA level
    engaged (``coll_han_numa_level``) the schedule nests a third,
    intra-domain phase instead."""
    _require_commutative(op, "allreduce")
    topo = topology(ctx, groups)
    if _use_numa(ctx, topo, "allreduce"):
        return _allreduce_numa(ctx, topo, value, op)
    intra, inter = _views(ctx, topo)
    if str(mca_var.get("coll_han_pipeline", "auto")) != "off" \
            and len(topo.groups) >= 2:
        geom = _pipeline_geometry(len(topo.groups), value)
        if geom is not None:
            return _allreduce_pipelined(intra, inter, value, op, geom)
    rank = getattr(ctx, "rank", -1)
    with ztrace.phase_span("intra", rank, op="allreduce"):
        partial = host.reduce(intra, value, op, root=0) \
            if intra.size > 1 else value
    full = None
    if inter is not None:
        full = _leader_allreduce(inter, partial, op)
    if intra.size > 1:
        with ztrace.phase_span("intra", rank, op="allreduce"):
            full = host.bcast(intra, full, root=0,
                              algorithm="binomial")
    return full


def _leader_allreduce(inter, partial: Any, op) -> Any:
    """The inter phase of allreduce.  Below ``host_coll_large_msg`` the
    flat allreduce runs as-is (recursive doubling — 2 leaders is its
    sweet spot).  Above it, the payload takes the SPLIT schedule —
    reduce-scatter + allgather across the leaders — processed in
    ``coll_han_inter_segment`` pieces so every wire transfer stays on
    the eager zero-copy path (segments are congruent across leaders:
    the geometry derives from the reduced payload, which the reduce
    phase made identical everywhere)."""
    if inter.size <= 1:
        return partial
    flightrec.record(flightrec.COLL_ENTER, op="allreduce",
                     phase="inter")
    with ztrace.phase_span("inter-host", getattr(inter, "rank", -1),
                           op="allreduce"):
        out = _leader_allreduce_body(inter, partial, op)
    flightrec.record(flightrec.COLL_EXIT, op="allreduce",
                     phase="inter")
    return out


def _leader_allreduce_body(inter, partial: Any, op) -> Any:
    large = int(mca_var.get("host_coll_large_msg", 256 * 1024))
    if (
        not isinstance(partial, np.ndarray)
        or partial.nbytes < large
        or partial.size < inter.size
    ):
        return host.allreduce(inter, partial, op)
    seg_bytes = max(1, int(mca_var.get("coll_han_inter_segment",
                                       1 << 20)))
    arr = np.ascontiguousarray(partial)
    flat = arr.reshape(-1)
    seg = max(inter.size, seg_bytes // max(arr.dtype.itemsize, 1))
    if flat.size <= seg:
        if inter.size > 2:
            tag = host._next_tag(inter, host.TAG_ALLREDUCE)
            return host._allreduce_ring(
                inter, flat, op, tag).reshape(arr.shape)
        return np.asarray(
            host.allreduce(inter, flat, op)).reshape(arr.shape)
    out = np.empty_like(flat)
    for off in range(0, flat.size, seg):
        piece = flat[off:off + seg]
        if inter.size > 2:
            tag = host._next_tag(inter, host.TAG_ALLREDUCE)
            done = host._allreduce_ring(inter, piece, op, tag)
        else:
            done = host.allreduce(inter, piece, op)
        out[off:off + seg] = np.asarray(done).reshape(-1)
    return out.reshape(arr.shape)


# -------------------------------------------------------------- bcast


def _bcast_numa(ctx, topo: _Topology, obj: Any, root: int) -> Any:
    """Three-level bcast: root → its domain leader (domain window) →
    its host leader (dleader window) → wire bcast among host leaders →
    dleader bcast → domain bcast.  Hop tags are consumed by every
    member of the hop's window (the two-level sequence-uniformity rule
    applied per level), and the hop conditions read only global
    topology, so every rank derives the identical schedule."""
    dview, dlview, wview = _numa_views(ctx, topo)
    spc.record("coll_han_numa_collectives", 1)
    orig = obj
    h_root, d_root = topo.domain_of(root)
    root_dom = topo.nested[h_root][d_root]
    droot_leader = root_dom[0]
    host_leader = topo.groups[h_root][0]
    # hop 1: root -> its domain's leader (all members of that domain
    # consume the tag; other domains' windows stay untouched)
    if root != droot_leader and ctx.rank in root_dom:
        hoptag = host._next_tag(dview, host.TAG_BCAST)
        if ctx.rank == root:
            dview.send(obj, 0, tag=hoptag)
        elif ctx.rank == droot_leader:
            obj = dview.recv(source=dview.rel_base(root), tag=hoptag)
    # hop 2: root's domain leader -> its host's leader (all that
    # host's domain leaders consume the dleader-window tag)
    if droot_leader != host_leader and topo.gidx == h_root \
            and dlview is not None:
        hoptag = host._next_tag(dlview, host.TAG_BCAST)
        if ctx.rank == droot_leader:
            dlview.send(obj, 0, tag=hoptag)
        elif ctx.rank == host_leader:
            obj = dlview.recv(source=dlview.rel_base(droot_leader),
                              tag=hoptag)
    if wview is not None and wview.size > 1:
        obj = host.bcast(wview, obj, algorithm="binomial",
                         root=topo.leaders.index(host_leader))
    if dlview is not None and dlview.size > 1:
        obj = host.bcast(dlview, obj, root=0, algorithm="binomial")
    out = host.bcast(dview, obj, root=0, algorithm="binomial") \
        if dview.size > 1 else obj
    # the root returns ITS payload (MPI buffer semantics), never the
    # round-tripped copy the down phases delivered back to it
    return orig if ctx.rank == root else out


@_recorded("bcast")
def bcast(ctx, obj: Any = None, root: int = 0,
          groups: list[list[int]] | None = None) -> Any:
    """Two-level bcast.  The leader set is FIXED (min rank per group,
    so every rank agrees on the tag windows with no negotiation); a
    non-leader root first hands the payload to its group's leader over
    the intra window — every member of that group consumes the hop tag
    so the window's sequence stays uniform."""
    topo = topology(ctx, groups)
    if _use_numa(ctx, topo, "bcast"):
        return _bcast_numa(ctx, topo, obj, root)
    intra, inter = _views(ctx, topo)
    root_g = topo.group_of(root)
    root_leader = topo.groups[root_g][0]
    if root != root_leader and topo.gidx == root_g:
        hoptag = host._next_tag(intra, host.TAG_BCAST)
        if ctx.rank == root:
            intra.send(obj, 0, tag=hoptag)
        elif ctx.rank == root_leader:
            obj = intra.recv(source=intra.rel(root), tag=hoptag)
    if inter is not None:
        obj = host.bcast(inter, obj, algorithm="binomial",
                         root=topo.leaders.index(root_leader))
    out = host.bcast(intra, obj, root=0, algorithm="binomial") \
        if intra.size > 1 else obj
    # the root returns ITS payload (MPI buffer semantics), not the
    # round-tripped copy the intra phase delivered back to it
    return obj if ctx.rank == root and root != root_leader else out


# -------------------------------------------------------------- reduce


@_recorded("reduce")
def reduce(ctx, value: Any, op, root: int = 0,
           groups: list[list[int]] | None = None) -> Any:
    """Two-level reduce: intra reduce → leader reduce rooted at the
    root's group leader → leader→root hop.  Result significant at root
    (others return None)."""
    _require_commutative(op, "reduce")
    topo = topology(ctx, groups)
    intra, inter = _views(ctx, topo)
    root_g = topo.group_of(root)
    root_leader = topo.groups[root_g][0]
    partial = host.reduce(intra, value, op, root=0) \
        if intra.size > 1 else value
    res = None
    if inter is not None:
        res = host.reduce(inter, partial, op,
                          root=topo.leaders.index(root_leader))
    if root != root_leader and topo.gidx == root_g:
        hoptag = host._next_tag(intra, host.TAG_REDUCE)
        if ctx.rank == root_leader:
            intra.send(res, intra.rel(root), tag=hoptag)
            res = None
        elif ctx.rank == root:
            res = intra.recv(source=0, tag=hoptag)
    return res if ctx.rank == root else None


# -------------------------------------------------------------- barrier


def _barrier_numa(ctx, topo: _Topology) -> None:
    """Three-level barrier: domain gather (arrival) → dleader gather →
    wire allgather among host leaders → dleader bcast → domain bcast
    (release).  No rank releases before every host's arrival reached
    the wire exchange."""
    dview, dlview, wview = _numa_views(ctx, topo)
    spc.record("coll_han_numa_collectives", 1)
    if dview.size > 1:
        host.gather(dview, b"", root=0)
    if dlview is not None:
        if dlview.size > 1:
            host.gather(dlview, b"", root=0)
        if wview is not None and wview.size > 1:
            host.allgather(wview, b"")
        if dlview.size > 1:
            host.bcast(dlview, b"", root=0, algorithm="binomial")
    if dview.size > 1:
        host.bcast(dview, b"", root=0, algorithm="binomial")


@_recorded("barrier")
def barrier(ctx, groups: list[list[int]] | None = None) -> None:
    """Two-level barrier: intra gather (arrival) → leader allgather →
    intra bcast (release) — p-1 sm hops plus the leader exchange,
    instead of log2(p) interleaved-transport dissemination rounds."""
    topo = topology(ctx, groups)
    if _use_numa(ctx, topo, "barrier"):
        return _barrier_numa(ctx, topo)
    intra, inter = _views(ctx, topo)
    if intra.size > 1:
        host.gather(intra, b"", root=0)
    if inter is not None and inter.size > 1:
        host.allgather(inter, b"")
    if intra.size > 1:
        host.bcast(intra, b"", root=0, algorithm="binomial")


# ------------------------------------------------------------ allgather


@_recorded("allgather")
def allgather(ctx, value: Any,
              groups: list[list[int]] | None = None) -> list:
    """Two-level allgather: intra gather → leader allgather (each block
    travels with its group's global rank map) → intra bcast of the
    assembled rank-indexed list."""
    topo = topology(ctx, groups)
    intra, inter = _views(ctx, topo)
    mine = host.gather(intra, value, root=0) \
        if intra.size > 1 else [value]
    out = None
    if inter is not None:
        blocks = host.allgather(inter, mine)
        out = [None] * ctx.size
        for gi, vals in enumerate(blocks):
            for g, v in zip(topo.groups[gi], vals):
                out[g] = v
    if intra.size > 1:
        out = host.bcast(intra, out, root=0, algorithm="binomial")
    return out


# -------------------------------------------------------- reduce_scatter


@_recorded("reduce_scatter")
def reduce_scatter(ctx, values: list, op,
                   groups: list[list[int]] | None = None) -> Any:
    """Two-level reduce_scatter: intra blockwise reduce → leader
    alltoall (leader j ships leader k the partials of k's group
    members) → per-block combine → intra scatter.  Rank r returns the
    fully-reduced block r."""
    _require_commutative(op, "reduce_scatter")
    if len(values) != ctx.size:
        raise errors.ArgError(
            f"reduce_scatter needs {ctx.size} blocks"
        )
    topo = topology(ctx, groups)
    intra, inter = _views(ctx, topo)
    partial = host.reduce(intra, list(values), op, root=0) \
        if intra.size > 1 else list(values)
    mine = None
    if inter is not None:
        send = [[partial[g] for g in topo.groups[k]]
                for k in range(len(topo.groups))]
        got = _leader_alltoall(inter, send)
        mine = got[0]
        for j in range(1, len(got)):
            mine = [host._combine(op, a, b)
                    for a, b in zip(mine, got[j])]
    if intra.size > 1:
        return host.scatter(intra, mine, root=0)
    return mine[0]


# --------------------------------------------------------------- alltoall


mca_var.register(
    "coll_han_alltoall_bruck_min", 8,
    "Leader count at which the han alltoall family's wire exchange "
    "switches from pairwise (one aggregated message per leader pair, "
    "p-1 rounds) to Bruck store-and-forward (ceil(log2 p) rounds, "
    "each forwarding up to half the aggregated blocks); 0 pins "
    "pairwise at every leader count",
    type=int,
)


def _leader_exchange_alg(inter) -> str:
    """Wire-exchange decision of the han alltoall family's leader
    phase: "pairwise" below ``coll_han_alltoall_bruck_min`` leaders,
    "bruck" at or above the bar.  Degrades loudly, never raises
    (ZL008): a malformed bar falls back to the registered default."""
    try:
        bar = int(mca_var.get("coll_han_alltoall_bruck_min", 8))
    except (TypeError, ValueError):
        mca_output.verbose(
            2, _stream,
            "coll_han_alltoall_bruck_min is not an integer; the "
            "default bar (8) applies",
        )
        bar = 8
    return "bruck" if bar > 0 and getattr(inter, "size", 0) >= bar \
        else "pairwise"


def _leader_alltoall(inter, send: list) -> list:
    """The aggregated leader exchange shared by alltoall/alltoallv and
    reduce_scatter's leader phase: each wire message carries a whole
    per-host block aggregate instead of the flat path's one message
    per cross-host RANK pair.  ``coll_han_alltoall_inter_bytes``
    accounts the payload this leader hands to the wire (its own block
    excluded); ``coll_han_alltoall_leader_msgs`` the wire messages it
    issues."""
    n, rank = inter.size, inter.rank
    spc.record(
        "coll_han_alltoall_inter_bytes",
        sum(payload_bytes(send[j]) for j in range(n) if j != rank),
    )
    if _leader_exchange_alg(inter) == "bruck":
        spc.record("coll_han_alltoall_leader_msgs",
                   max(0, (n - 1).bit_length()))
        tag = host._next_tag(inter, host.TAG_ALLTOALL)
        return host._alltoall_bruck(inter, list(send), tag)
    spc.record("coll_han_alltoall_leader_msgs", max(0, n - 1))
    return host.alltoall(inter, send)


def _alltoall_blocks(ctx, topo: _Topology, blocks: list) -> list:
    """The shared three-phase block schedule: intra gather of each
    member's full rank-indexed send list to its leader → leader j
    ships leader k the [src-in-j × dst-in-k] block matrix through
    ``_leader_alltoall`` → intra scatter of each member's reassembled
    rank-indexed receive list.  Intra traffic grows (every list rides
    the sm rings twice) to buy the wire aggregation — the han trade."""
    intra, inter = _views(ctx, topo)
    spc.record("coll_han_alltoall_collectives", 1)
    rank = getattr(ctx, "rank", -1)
    with ztrace.phase_span("intra", rank, op="alltoall"):
        lists = host.gather(intra, blocks, root=0) \
            if intra.size > 1 else [blocks]
    recv_lists = None
    if inter is not None:
        members = topo.groups[topo.gidx]
        send = [[[lists[si][d] for d in topo.groups[k]]
                 for si in range(len(members))]
                for k in range(len(topo.groups))]
        flightrec.record(flightrec.COLL_ENTER, op="alltoall",
                         phase="inter")
        with ztrace.phase_span("inter-host", getattr(inter, "rank", -1),
                               op="alltoall"):
            got = _leader_alltoall(inter, send)
        flightrec.record(flightrec.COLL_EXIT, op="alltoall",
                         phase="inter")
        # got[j][si][di]: the block global rank topo.groups[j][si] sent
        # to the di-th member of MY group — reassemble one rank-indexed
        # receive list per member
        recv_lists = []
        for di in range(len(members)):
            out: list = [None] * ctx.size
            for j, srcs in enumerate(topo.groups):
                for si, src in enumerate(srcs):
                    out[src] = got[j][si][di]
            recv_lists.append(out)
    elif len(topo.groups) == 1 and getattr(intra, "rank", -1) == 0:
        # forced single-group topology: no wire phase — the leader
        # holds every member's list already
        members = topo.groups[0]
        recv_lists = []
        for di in range(len(members)):
            out = [None] * ctx.size
            for si, src in enumerate(members):
                out[src] = lists[si][members[di]]
            recv_lists.append(out)
    if intra.size > 1:
        with ztrace.phase_span("intra", rank, op="alltoall"):
            return host.scatter(intra, recv_lists, root=0)
    return recv_lists[0]


@_recorded("alltoall")
def alltoall(ctx, values: list,
             groups: list[list[int]] | None = None) -> list:
    """Two-level alltoall: see ``_alltoall_blocks``.  ``values`` is the
    rank-indexed send list; returns the rank-indexed receive list (the
    flat contract of ``coll/host.py``)."""
    if len(values) != ctx.size:
        raise errors.ArgError(f"alltoall needs {ctx.size} blocks")
    topo = topology(ctx, groups)
    return _alltoall_blocks(ctx, topo, list(values))


@_recorded("alltoallv")
def alltoallv(ctx, sendbuf, counts: list, displs: list | None = None,
              groups: list[list[int]] | None = None) -> list:
    """Two-level alltoallv: the flat (counts, displs) slicing of
    ``coll/host.py`` feeds the shared block schedule — variable-size
    blocks ride the aggregated leader exchange unchanged (host-plane
    objects carry their own size)."""
    blocks = host._blocks_from(sendbuf, counts, displs, ctx.size)
    topo = topology(ctx, groups)
    return _alltoall_blocks(ctx, topo, blocks)
