"""Python-plane shared-memory transport (pt2pt/sm.py) — the twin of
tests/test_sm_transport.py's C-plane contract, plus the mmap ring
itself: segment lifecycle (files live exactly as long as their proc,
stale rings unlinked at create), btl-style priority selection with
loud degradation to TCP for mixed pairs, and an
eager/fragmented/zero-size/non-contiguous roundtrip matrix over the
ring."""

import os
import threading

import numpy as np
import pytest

from zhpe_ompi_tpu.mca import var as mca_var
from zhpe_ompi_tpu.pt2pt import sm as sm_mod
from zhpe_ompi_tpu.pt2pt.tcp import TcpProc
from zhpe_ompi_tpu.runtime import spc


def run_sm(n, fn, kwargs_by_rank=None, timeout=60.0, **common):
    """Launch n TcpProcs in threads sharing a localhost coordinator,
    with per-rank constructor overrides (the asymmetric-config knob the
    mixed-pair tests need)."""
    coord_ready = threading.Event()
    coord_addr = [None]
    results = [None] * n
    excs = [None] * n

    def main(rank):
        kw = dict(common)
        kw.update((kwargs_by_rank or {}).get(rank, {}))
        try:
            if rank == 0:
                proc = TcpProc(
                    0, n, coordinator=("127.0.0.1", 0),
                    on_coordinator_bound=lambda a: (
                        coord_addr.__setitem__(0, a), coord_ready.set()),
                    **kw)
            else:
                coord_ready.wait(10)
                proc = TcpProc(rank, n, coordinator=coord_addr[0], **kw)
            try:
                results[rank] = fn(proc)
            finally:
                proc.close()
        except BaseException as e:  # noqa: BLE001
            excs[rank] = e
            coord_ready.set()

    threads = [threading.Thread(target=main, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout)
        assert not t.is_alive(), "sm rank hung"
    if any(e is not None for e in excs):
        raise next(e for e in excs if e is not None)
    return results


class TestRing:
    """The mmap ring itself, below the transport: SPSC framing, wrap,
    fragment pipeline, and geometry adoption."""

    def _pair(self, collected, nslots=4, slot_bytes=256):
        mca_var.set_var("sm_max_frag", slot_bytes)
        mca_var.set_var("sm_ring_bytes", nslots * slot_bytes)
        seg = sm_mod.SmSegment(
            0, 2, on_frame=lambda src, frame: collected.append(
                (src, bytes(frame))))
        tx = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
        return seg, tx

    def _send_bytes(self, tx, blob, deadline=5.0):
        import time

        return tx.send_frame(blob, [], time.monotonic() + deadline,
                             None)

    def _await(self, collected, count, timeout=5.0):
        import time

        deadline = time.monotonic() + timeout
        while len(collected) < count and time.monotonic() < deadline:
            time.sleep(0.001)
        assert len(collected) >= count, (
            f"only {len(collected)}/{count} frames arrived")

    def test_roundtrip_and_wraparound(self, fresh_vars):
        collected = []
        seg, tx = self._pair(collected)
        try:
            # 4-slot ring, far more frames than slots: head/tail wrap
            frames = [bytes([i]) * (i * 37 % 200) for i in range(64)]
            for f in frames:
                self._send_bytes(tx, f)
            self._await(collected, len(frames))
            assert [f for _, f in collected] == frames
            assert all(src == 1 for src, _ in collected)
        finally:
            tx.close()
            seg.close()
        assert not os.path.exists(seg.path)

    def test_message_larger_than_whole_ring_streams(self, fresh_vars):
        collected = []
        seg, tx = self._pair(collected, nslots=4, slot_bytes=256)
        try:
            big = bytes(range(256)) * 40  # 10 KiB through a 1 KiB ring
            wire, nfrags = self._send_bytes(tx, big, deadline=10.0)
            assert nfrags == 40
            assert wire == len(big) + nfrags * 16
            self._await(collected, 1, timeout=10.0)
            assert collected[0][1] == big
        finally:
            tx.close()
            seg.close()

    def test_full_ring_spins_attribute_to_the_sending_thread(
            self, fresh_vars):
        """The thread-local full-spin accumulator (the ztrace sm span's
        per-call `bp` source) rises on the thread that actually spun on
        a full ring and stays flat on every other thread — the global
        sm_ring_full_spins counter cannot make that distinction."""
        import time

        release = threading.Event()
        collected = []

        def on_frame(src, frame):
            release.wait(10.0)  # park the consumer: tail never advances
            collected.append((src, bytes(frame)))

        mca_var.set_var("sm_max_frag", 256)
        mca_var.set_var("sm_ring_bytes", 4 * 256)
        seg = sm_mod.SmSegment(0, 2, on_frame=on_frame)
        tx = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
        try:
            base = sm_mod.thread_full_spins()
            for _ in range(4):  # fill the ring behind the parked consumer
                tx.send_frame(b"x" * 64, [], time.monotonic() + 5.0,
                              None)
            with pytest.raises(sm_mod.RingFull):
                tx.send_frame(b"y" * 64, [], time.monotonic() + 0.3,
                              None)
            assert sm_mod.thread_full_spins() > base
            sibling = []
            t = threading.Thread(
                target=lambda: sibling.append(sm_mod.thread_full_spins()))
            t.start()
            t.join(5.0)
            assert sibling == [0]  # another thread's view: no spins
        finally:
            release.set()
            tx.close()
            seg.close()

    def test_zero_size_frame(self, fresh_vars):
        collected = []
        seg, tx = self._pair(collected)
        try:
            wire, nfrags = self._send_bytes(tx, b"")
            assert nfrags == 1
            self._await(collected, 1)
            assert collected[0][1] == b""
        finally:
            tx.close()
            seg.close()

    def test_sender_adopts_segment_geometry(self, fresh_vars):
        """Geometry is read from the SEGMENT header, not the mapper's
        MCA state: a var mismatch between procs cannot desync the
        slot walk (the cross-process contract)."""
        collected = []
        seg, _tx0 = self._pair(collected, nslots=8, slot_bytes=128)
        _tx0.close()
        # a sender created under totally different local vars
        mca_var.set_var("sm_max_frag", 4096)
        mca_var.set_var("sm_ring_bytes", 1 << 20)
        tx = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
        try:
            assert tx.slot_bytes == 128 and tx.nslots == 8
            blob = bytes(1000)
            _wire, nfrags = self._send_bytes(tx, blob)
            assert nfrags == 8  # 1000 bytes over 128-byte slots
            self._await(collected, 1)
            assert collected[0][1] == blob
        finally:
            tx.close()
            seg.close()

    def test_stale_ring_unlinked_at_create(self, fresh_vars):
        """The O_EXCL-retry idiom (zompi_mpi.cpp:709): a leftover file
        from a crashed job with the same name is unlinked and the
        create retried, not an error and not silently reused."""
        collected = []
        name = "zompi_pyring_testsuite_stale_0_0"
        path = os.path.join(sm_mod.segment_dir(), name)
        with open(path, "wb") as f:
            f.write(b"stale garbage from a crashed job")
        try:
            seg = sm_mod.SmSegment(0, 2, on_frame=lambda s, fr: None,
                                   name=name)
            try:
                # recreated from scratch: mappable, right geometry
                tx = sm_mod.SmSender(name, src_rank=1, dest_rank=0)
                tx.close()
            finally:
                seg.close()
            assert not os.path.exists(path)
        finally:
            if os.path.exists(path):
                os.unlink(path)

    def test_foreign_file_refused(self, fresh_vars):
        name = "zompi_pyring_testsuite_foreign_0_0"
        path = os.path.join(sm_mod.segment_dir(), name)
        with open(path, "wb") as f:
            f.write(b"\x00" * 8192)
        try:
            from zhpe_ompi_tpu.core import errors

            with pytest.raises(errors.MpiError):
                sm_mod.SmSender(name, src_rank=0, dest_rank=1)
        finally:
            os.unlink(path)


class TestTransportMatrix:
    """The ring under the full TcpProc surface: every payload shape the
    DSS wire carries round-trips over sm, across the eager/fragment
    regimes, with zero silent TCP fallback."""

    PAYLOADS = [
        b"",                                     # zero-size
        0,
        3.14,
        "string payload",
        b"x" * 100,
        np.array([], dtype=np.float32),          # zero-size array
        np.arange(1000, dtype=np.float64),       # eager OOB array
        np.arange(4096, dtype=np.float64)[::2],  # NON-contiguous
        np.float64(2.5),                         # numpy scalar
        (7, np.ones(128, np.float32)),           # (idx, block) tuple
        {"k": [1, np.arange(10)], "n": None},    # nested mix
        np.arange(1 << 16, dtype=np.float64),    # 512 KiB: fragmented
    ]

    def test_roundtrip_matrix_rides_the_ring(self, fresh_vars):
        fb0 = spc.read("sm_fallback_tcp_sends")
        eager0 = spc.read("sm_eager_sends")
        frag0 = spc.read("sm_frag_sends")

        def prog(p):
            other = 1 - p.rank
            for i, m in enumerate(self.PAYLOADS):
                p.send(m, dest=other, tag=100 + i)
            got = [p.recv(source=other, tag=100 + i, timeout=30.0)
                   for i in range(len(self.PAYLOADS))]
            p.barrier()
            return got

        res = run_sm(2, prog, sm=True)
        for got in res:
            for sent, back in zip(self.PAYLOADS, got):
                if isinstance(sent, np.ndarray):
                    assert np.array_equal(np.ascontiguousarray(sent),
                                          back)
                    assert back.flags.writeable
                elif isinstance(sent, tuple):
                    assert back[0] == sent[0]
                    assert np.array_equal(sent[1], back[1])
                elif isinstance(sent, dict):
                    assert back["n"] is None
                    assert np.array_equal(sent["k"][1], back["k"][1])
                else:
                    assert back == sent
        assert spc.read("sm_fallback_tcp_sends") == fb0
        assert spc.read("sm_eager_sends") > eager0
        assert spc.read("sm_frag_sends") > frag0  # the 512 KiB rung

    def test_large_rendezvous_regime_rides_the_ring(self, fresh_vars):
        """Above tcp_eager_limit the wire would switch to RTS/CTS; the
        sm plane carries the same payload as a fragment pipeline with
        ring backpressure as its receiver-memory bound — no RTS ever
        crosses, and the bytes all ride the ring."""
        big = np.arange(1 << 18, dtype=np.float64)  # 2 MB > eager limit
        rndv0 = spc.read("tcp_rndv_sends")
        sent0 = spc.read("sm_bytes_sent")

        def prog(p):
            if p.rank == 0:
                p.send(big, dest=1, tag=7)
                return True
            got = p.recv(source=0, tag=7, timeout=30.0)
            return bool(np.array_equal(got, big)) and got.flags.writeable

        assert run_sm(2, prog, sm=True) == [True, True]
        assert spc.read("tcp_rndv_sends") == rndv0
        assert spc.read("sm_bytes_sent") - sent0 >= big.nbytes

    def test_collectives_get_the_fast_path_for_free(self, fresh_vars):
        """coll/host rides the same send seam: a 4-rank ring allreduce
        moves its chunks over the rings, no code changes above the
        transport (the coll-rides-the-PML layering)."""
        from zhpe_ompi_tpu import ops

        sent0 = spc.read("sm_bytes_sent")
        fb0 = spc.read("sm_fallback_tcp_sends")
        arr = np.full(4096, 1.0)

        def prog(p):
            out = p.allreduce(arr * (p.rank + 1), ops.SUM)
            p.barrier()
            return float(np.asarray(out)[0])

        assert run_sm(4, prog, sm=True, timeout=90.0) == [10.0] * 4
        assert spc.read("sm_bytes_sent") > sent0
        assert spc.read("sm_fallback_tcp_sends") == fb0

    def test_ordering_under_concurrent_tags(self, fresh_vars):
        """Per-source FIFO across eager and fragmented messages on one
        direction: interleaved sizes deliver in matching order."""

        def prog(p):
            other = 1 - p.rank
            sizes = [10, 1 << 15, 4, 1 << 16, 0, 300]
            for i, nb in enumerate(sizes):
                p.send(np.arange(max(1, nb // 8), dtype=np.float64)
                       if nb else b"", dest=other, tag=50 + i)
            out = []
            for i, nb in enumerate(sizes):
                got = p.recv(source=other, tag=50 + i, timeout=30.0)
                out.append(got if isinstance(got, bytes)
                           else int(got.size))
            p.barrier()
            return out

        res = run_sm(2, prog, sm=True)
        expect = [1, 4096, 1, 8192, b"", 37]
        assert res == [expect, expect]


class TestSelection:
    """btl-style priority selection and the mixed-pair degradation
    contract (the Python twin of test_sm_transport.py's
    test_mixed_on_off_degrades_to_tcp)."""

    def _exchange(self, p):
        other = 1 - p.rank
        msgs = [p.rank, np.arange(256.0), b"z" * 8192,
                np.zeros(1 << 15)]
        for i, m in enumerate(msgs):
            p.send(m, dest=other, tag=20 + i)
        got = [p.recv(source=other, tag=20 + i, timeout=30.0)
               for i in range(len(msgs))]
        p.barrier()
        # exactly-once: a second recv on any tag must find nothing
        for i in range(len(msgs)):
            assert p.probe(source=other, tag=20 + i) is None or \
                not p.probe(source=other, tag=20 + i)
        return (got[0], float(np.asarray(got[1]).sum()), len(got[2]),
                int(np.asarray(got[3]).size))

    EXPECT = [(1, np.arange(256.0).sum(), 8192, 1 << 15),
              (0, np.arange(256.0).sum(), 8192, 1 << 15)]

    def test_sm_selected_by_default_same_boot(self, fresh_vars):
        sent0 = spc.read("sm_bytes_sent")
        assert run_sm(2, self._exchange, sm=True) == self.EXPECT
        assert spc.read("sm_bytes_sent") > sent0

    def test_mixed_pair_degrades_without_loss(self, fresh_vars):
        """sm=1 on one side, sm=0 on the other: no ring activates in
        either direction, every message still arrives exactly once,
        and the degradation is intentional (no fallback counted —
        the peer never advertised)."""
        fb0 = spc.read("sm_fallback_tcp_sends")
        sent0 = spc.read("sm_bytes_sent")
        res = run_sm(2, self._exchange,
                     kwargs_by_rank={0: {"sm": True}, 1: {"sm": False}})
        assert res == self.EXPECT
        assert spc.read("sm_bytes_sent") == sent0
        assert spc.read("sm_fallback_tcp_sends") == fb0

    def test_mismatched_boot_id_degrades_loudly(self, fresh_vars):
        """Both sides advertise rings but the boot ids differ (not
        provably one /dev/shm namespace): the pair degrades to TCP
        without loss AND the degradation is visible in
        sm_fallback_tcp_sends."""
        fb0 = spc.read("sm_fallback_tcp_sends")
        res = run_sm(
            2, self._exchange,
            kwargs_by_rank={0: {"sm": True},
                            1: {"sm": True,
                                "sm_boot_id": "feedfacef00d"}})
        assert res == self.EXPECT
        assert spc.read("sm_fallback_tcp_sends") > fb0

    def test_priority_ladder_tcp_can_outrank_sm(self, fresh_vars):
        """sm_priority <= tcp_priority forces the wire path per policy
        (btl priority selection), with the rings still created — and
        NOT counted as silent fallback."""
        mca_var.set_var("sm_priority", 10)
        mca_var.set_var("tcp_priority", 20)
        fb0 = spc.read("sm_fallback_tcp_sends")
        sent0 = spc.read("sm_bytes_sent")
        assert run_sm(2, self._exchange, sm=True) == self.EXPECT
        assert spc.read("sm_bytes_sent") == sent0
        assert spc.read("sm_fallback_tcp_sends") == fb0

    def test_malformed_card_degrades_not_raises(self):
        """Modex cards are relayed verbatim from arbitrary peers: a
        capability item wearing our prefix but malformed must degrade
        the pair to TCP, never raise out of endpoint selection."""
        assert sm_mod.parse_card(["h", 1, "pyshm:abc"]) is None
        assert sm_mod.parse_card(["h", 1, "pyshm:"]) is None
        assert sm_mod.parse_card(["h", 1, "pyshm::name"]) is None
        assert sm_mod.parse_card(["h", 1, "pyshm:boot:"]) is None
        assert sm_mod.parse_card(["h", 1, "sm"]) is None  # C-plane cap
        assert sm_mod.parse_card(["h", 1]) is None
        assert sm_mod.parse_card(None) is None
        assert sm_mod.parse_card(
            ["h", 1, "sm", "pyshm:boot:name"]) == ("boot", "name")

    def test_mca_sm_zero_disables_globally(self, fresh_vars):
        mca_var.set_var("sm", 0)
        sent0 = spc.read("sm_bytes_sent")
        assert run_sm(2, self._exchange) == self.EXPECT
        assert spc.read("sm_bytes_sent") == sent0


class TestLifecycle:
    """The operational contract of test_sm_transport.py on the Python
    plane: segments exist only while a job lives and are unlinked at
    close; nothing leaks."""

    def test_segments_unlinked_at_close(self, fresh_vars):
        seen = []

        def prog(p):
            if p._sm_seg is not None:
                seen.append(p._sm_seg.path)
                assert os.path.exists(p._sm_seg.path)
            p.send(p.rank, dest=(p.rank + 1) % 3, tag=1)
            p.recv(source=(p.rank - 1) % 3, tag=1, timeout=30.0)
            p.barrier()
            return True

        assert run_sm(3, prog, sm=True) == [True] * 3
        assert len(seen) == 3
        for path in seen:
            assert not os.path.exists(path), f"{path} leaked past close"
        assert sm_mod.orphaned_ring_files() == []
        assert sm_mod.live_poll_threads() == []

    def test_failed_construction_leaks_nothing(self, fresh_vars):
        """A proc whose modex never completes (unreachable coordinator)
        raises out of the constructor — nobody will ever call close()
        on it, so the constructor itself must unwind the segment and
        poll thread (zero-orphan contract)."""
        from zhpe_ompi_tpu.core import errhandler as errh
        from zhpe_ompi_tpu.core import errors

        before = set(sm_mod.orphaned_ring_files())
        with pytest.raises((errors.MpiError, errh.JobAbort)):
            TcpProc(1, 2, coordinator=("127.0.0.1", 1), timeout=0.5,
                    sm=True)
        assert set(sm_mod.orphaned_ring_files()) == before
        assert sm_mod.live_poll_threads() == []

    def test_forced_off_creates_no_segments(self, fresh_vars):
        def prog(p):
            assert p._sm_seg is None
            p.barrier()
            return True

        before = set(sm_mod.orphaned_ring_files())
        assert run_sm(2, prog, sm=False) == [True, True]
        assert set(sm_mod.orphaned_ring_files()) == before


class TestPackFramesInto:
    """The write-into-buffer pack variant the single-slot fast path
    uses (satellite on utils/dss.py) at its call site: small frames
    pack their header straight into slot memory."""

    def test_direct_path_taken_for_small_frames(self, fresh_vars):
        eager0 = spc.read("sm_eager_sends")

        def prog(p):
            if p.rank == 0:
                p.send(np.arange(64.), dest=1, tag=3)
                return True
            got = p.recv(source=0, tag=3, timeout=30.0)
            return float(got.sum())

        res = run_sm(2, prog, sm=True)
        assert res[1] == float(np.arange(64.).sum())
        assert spc.read("sm_eager_sends") > eager0


class TestDemandMapping:
    """The ring directory: rings materialize on first contact (the
    doorbell allocate handshake), per-class geometry comes from the
    OWNER's directory entry, footprint tracks the allocation bitmap,
    and the close-time audit holds."""

    def test_no_rings_for_silent_peers(self, fresh_vars):
        """A proc that never receives from a peer never pays that
        peer's ring: only the demanded ring materializes, and the
        logical footprint stays far below the size×ring pre-carve."""
        collected = []
        seg = sm_mod.SmSegment(0, 16, on_frame=lambda s, f:
                               collected.append(s))
        try:
            assert seg.materialized() == []
            tx = sm_mod.SmSender(seg.name, src_rank=5, dest_rank=0)
            try:
                tx.send_frame(b"x" * 100, [], _deadline(), None)
                _await_count(collected, 1)
                assert seg.materialized() == [5]
                ring = int(mca_var.get("sm_ring_bytes", 4 << 20))
                assert seg.footprint_bytes() < 2 * ring
                phys = seg.physical_bytes()
                assert phys is not None and phys < 2 * ring
            finally:
                tx.close()
        finally:
            seg.close()
        assert sm_mod.segment_audit_failures() == []

    def test_leader_class_ring_geometry(self, fresh_vars):
        """The LEADER peer class sizes its ring by
        sm_leader_ring_bytes — geometry decided by the OWNER at
        materialization, adopted by the sender from the directory."""
        mca_var.set_var("sm_max_frag", 1024)
        mca_var.set_var("sm_ring_bytes", 16 * 1024)
        mca_var.set_var("sm_leader_ring_bytes", 4 * 1024)
        seg = sm_mod.SmSegment(0, 3, on_frame=lambda s, f: None)
        try:
            intra = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0,
                                    ring_class=sm_mod.CLASS_INTRA)
            leader = sm_mod.SmSender(seg.name, src_rank=2, dest_rank=0,
                                     ring_class=sm_mod.CLASS_LEADER)
            try:
                assert (intra.nslots, intra.slot_bytes) == (16, 1024)
                assert (leader.nslots, leader.slot_bytes) == (4, 1024)
            finally:
                intra.close()
                leader.close()
        finally:
            seg.close()
        assert sm_mod.segment_audit_failures() == []

    def test_handshake_wakes_a_dozing_consumer(self, fresh_vars):
        """First contact while the poll thread is parked in its futex
        doze: the allocation request rings the doorbell and the ring
        materializes promptly."""
        import time

        collected = []
        seg = sm_mod.SmSegment(0, 2, on_frame=lambda s, f:
                               collected.append(bytes(f)))
        try:
            time.sleep(0.2)  # poll thread is long past its hot window
            t0 = time.monotonic()
            tx = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
            took = time.monotonic() - t0
            try:
                assert took < 2.0, f"handshake took {took:.3f}s"
                tx.send_frame(b"after doze", [], _deadline(), None)
                _await_count(collected, 1)
                assert collected[0] == b"after doze"
            finally:
                tx.close()
        finally:
            seg.close()

    def test_consumer_stopped_fails_the_handshake(self, fresh_vars):
        seg = sm_mod.SmSegment(0, 2, on_frame=lambda s, f: None)
        seg.sever()  # poll loop exits, STOPPED flag up, file survives
        try:
            with pytest.raises(sm_mod.ConsumerStopped):
                sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
        finally:
            seg.close()
        # a severed segment is a crash: the audit is skipped by design
        assert sm_mod.segment_audit_failures() == []

    def test_sender_recreation_adopts_existing_ring(self, fresh_vars):
        """A second sender for the same source rank adopts the already
        materialized ring (geometry AND head position), it does not
        re-request."""
        collected = []
        seg = sm_mod.SmSegment(0, 2, on_frame=lambda s, f:
                               collected.append(bytes(f)))
        try:
            tx = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
            tx.send_frame(b"first", [], _deadline(), None)
            tx.close()
            tx2 = sm_mod.SmSender(seg.name, src_rank=1, dest_rank=0)
            try:
                tx2.send_frame(b"second", [], _deadline(), None)
                _await_count(collected, 2)
                assert collected == [b"first", b"second"]
                assert seg.materialized() == [1]
            finally:
                tx2.close()
        finally:
            seg.close()
        assert sm_mod.segment_audit_failures() == []

    def test_wire_rings_follow_traffic(self, fresh_vars):
        """Over the full transport: a 4-rank job where only the 0↔1
        and 2↔3 pairs exchange data materializes exactly those rings
        in each proc's segment."""
        def prog(p):
            # no barrier anywhere: a barrier's dissemination tree
            # would materialize rings across pairs (correctly!) and
            # race the probe below — the pairwise recv IS the sync
            peer = p.rank ^ 1
            p.send(("hello", p.rank), peer, tag=7)
            got = p.recv(source=peer, tag=7)
            stats = p.sm_segment_stats()
            return got, stats["materialized"]

        res = run_sm(4, prog)
        for r, (got, mat) in enumerate(res):
            assert got == ("hello", r ^ 1)
            assert mat == [r ^ 1], (r, mat)

    def test_numa_classed_rings_at_the_seam(self, fresh_vars):
        """Cross-domain same-host pairs get LEADER-class rings at the
        transport seam (sm_numa_id emulation), same-domain pairs get
        the intra class."""
        mca_var.set_var("sm_max_frag", 4096)
        mca_var.set_var("sm_ring_bytes", 64 * 1024)
        mca_var.set_var("sm_leader_ring_bytes", 16 * 1024)
        kw = {r: {"sm_numa_id": f"d{r // 2}"} for r in range(4)}

        def prog(p):
            # talk to a same-domain sibling and a cross-domain peer
            sib, cross = p.rank ^ 1, p.rank ^ 2
            for peer in (sib, cross):
                p.send(b"ping", peer, tag=3)
            got = sorted(bytes(p.recv(source=s, tag=3))
                         for s in (sib, cross))
            smtx_sib = p._sm_tx(sib)
            smtx_cross = p._sm_tx(cross)
            out = (smtx_sib.nslots, smtx_cross.nslots)
            p.barrier()
            return got, out

        for got, (sib_slots, cross_slots) in run_sm(4, prog, kw):
            assert got == [b"ping", b"ping"]
            assert sib_slots == 16    # 64K intra ring / 4K slots
            assert cross_slots == 4   # 16K leader ring / 4K slots

    def test_audit_flags_orphaned_request(self, fresh_vars):
        """A request the owner never served (stuck REQUESTED entry at
        clean close) is an orphaned directory entry: the audit must
        say so.  Injected by writing the request AFTER the poll thread
        stopped — then the recorded failure is cleared so the session
        gate stays green."""
        import struct as _struct

        seg = sm_mod.SmSegment(0, 2, on_frame=lambda s, f: None)
        seg._stop.set()
        seg._poll.join(timeout=5.0)
        off = seg._dirent(1)
        _struct.Struct("<I").pack_into(seg._mm, off, 1)  # REQUESTED
        seg.close()
        fails = sm_mod.segment_audit_failures()
        assert any("never materialized" in f for f in fails), fails
        with sm_mod._registry_lock:
            sm_mod._audit_failures.clear()


def _deadline(s: float = 5.0):
    import time

    return time.monotonic() + s


def _await_count(collected, count, timeout=5.0):
    import time

    deadline = time.monotonic() + timeout
    while len(collected) < count and time.monotonic() < deadline:
        time.sleep(0.001)
    assert len(collected) >= count, (
        f"only {len(collected)}/{count} frames arrived")
