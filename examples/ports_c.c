/* ports_c.c — round-5 dynamic-process tier-2 acceptance: ports
 * (open/accept/connect/disconnect), the launcher name service
 * (publish/lookup/unpublish), MPI_Comm_join over a raw socket, the
 * general MPI_Dist_graph_create, and predefined attr callbacks.
 * Reference shapes: ompi/mpi/c/{open_port,comm_accept,comm_connect,
 * publish_name,comm_join,dist_graph_create,attr_fn}.c.
 * Run with >= 2 ranks under zmpirun (the name server lives there). */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>
#include <arpa/inet.h>
#include <sys/socket.h>
#include "zompi_mpi.h"

#define CHECK(cond)                                                    \
  do {                                                                 \
    if (!(cond)) {                                                     \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond);  \
      MPI_Abort(MPI_COMM_WORLD, 2);                                    \
    }                                                                  \
  } while (0)

int main(int argc, char **argv) {
  CHECK(MPI_Init(&argc, &argv) == MPI_SUCCESS);
  int rank, size;
  MPI_Comm_rank(MPI_COMM_WORLD, &rank);
  MPI_Comm_size(MPI_COMM_WORLD, &size);
  CHECK(size >= 2);

  /* ---- split into server half (low) and client half (high) ---- */
  int half = size / 2;
  int is_server = rank < half;
  MPI_Comm side;
  CHECK(MPI_Comm_split(MPI_COMM_WORLD, is_server, rank, &side) ==
        MPI_SUCCESS);

  /* ---- ports + name service ---- */
  {
    char svc[64];
    snprintf(svc, sizeof svc, "zompi-ports-demo-%s",
             getenv("ZMPI_COORD_PORT") ? getenv("ZMPI_COORD_PORT") : "0");
    MPI_Comm inter = MPI_COMM_NULL;
    if (is_server) {
      char port[MPI_MAX_PORT_NAME] = {0};
      if (rank == 0) {
        CHECK(MPI_Open_port(MPI_INFO_NULL, port) == MPI_SUCCESS);
        CHECK(strchr(port, ':') != NULL);
        CHECK(MPI_Publish_name(svc, MPI_INFO_NULL, port) ==
              MPI_SUCCESS);
      }
      MPI_Barrier(MPI_COMM_WORLD); /* clients may look up now */
      CHECK(MPI_Comm_accept(port, MPI_INFO_NULL, 0, side, &inter) ==
            MPI_SUCCESS);
      if (rank == 0) {
        CHECK(MPI_Unpublish_name(svc, MPI_INFO_NULL, port) ==
              MPI_SUCCESS);
        CHECK(MPI_Close_port(port) == MPI_SUCCESS);
      }
    } else {
      char port[MPI_MAX_PORT_NAME] = {0};
      MPI_Barrier(MPI_COMM_WORLD); /* wait for the publication */
      if (rank == half)
        CHECK(MPI_Lookup_name(svc, MPI_INFO_NULL, port) == MPI_SUCCESS);
      CHECK(MPI_Comm_connect(port, MPI_INFO_NULL, 0, side, &inter) ==
            MPI_SUCCESS);
    }
    /* intercomm sanity: sizes and a remote-group exchange */
    int lsz = -1, rsz = -1, flag = 0;
    CHECK(MPI_Comm_test_inter(inter, &flag) == MPI_SUCCESS && flag);
    CHECK(MPI_Comm_size(inter, &lsz) == MPI_SUCCESS);
    CHECK(MPI_Comm_remote_size(inter, &rsz) == MPI_SUCCESS);
    CHECK(lsz == (is_server ? half : size - half));
    CHECK(rsz == (is_server ? size - half : half));
    int me_local = -1;
    MPI_Comm_rank(inter, &me_local);
    if (me_local == 0) {
      int token = is_server ? 111 : 222, got = -1;
      MPI_Status st;
      CHECK(MPI_Sendrecv(&token, 1, MPI_INT, 0, 9, &got, 1, MPI_INT, 0,
                         9, inter, &st) == MPI_SUCCESS);
      CHECK(got == (is_server ? 222 : 111));
    }
    CHECK(MPI_Comm_disconnect(&inter) == MPI_SUCCESS &&
          inter == MPI_COMM_NULL);
  }

  /* ---- Comm_join between ranks 0 and 1 over a raw TCP socket ---- */
  if (rank < 2) {
    int sock = -1;
    if (rank == 0) {
      int srv = socket(AF_INET, SOCK_STREAM, 0);
      struct sockaddr_in a;
      memset(&a, 0, sizeof a);
      a.sin_family = AF_INET;
      a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
      a.sin_port = 0;
      CHECK(bind(srv, (struct sockaddr *)&a, sizeof a) == 0);
      CHECK(listen(srv, 1) == 0);
      socklen_t alen = sizeof a;
      getsockname(srv, (struct sockaddr *)&a, &alen);
      int p = (int)ntohs(a.sin_port);
      CHECK(MPI_Send(&p, 1, MPI_INT, 1, 77, MPI_COMM_WORLD) ==
            MPI_SUCCESS);
      sock = accept(srv, NULL, NULL);
      CHECK(sock >= 0);
      close(srv);
    } else {
      int p = -1;
      CHECK(MPI_Recv(&p, 1, MPI_INT, 0, 77, MPI_COMM_WORLD,
                     MPI_STATUS_IGNORE) == MPI_SUCCESS);
      sock = socket(AF_INET, SOCK_STREAM, 0);
      struct sockaddr_in a;
      memset(&a, 0, sizeof a);
      a.sin_family = AF_INET;
      a.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
      a.sin_port = htons((unsigned short)p);
      CHECK(connect(sock, (struct sockaddr *)&a, sizeof a) == 0);
    }
    MPI_Comm joined = MPI_COMM_NULL;
    CHECK(MPI_Comm_join(sock, &joined) == MPI_SUCCESS);
    close(sock);
    int rsz = -1;
    CHECK(MPI_Comm_remote_size(joined, &rsz) == MPI_SUCCESS &&
          rsz == 1);
    int token = 500 + rank, got = -1;
    CHECK(MPI_Sendrecv(&token, 1, MPI_INT, 0, 8, &got, 1, MPI_INT, 0, 8,
                       joined, MPI_STATUS_IGNORE) == MPI_SUCCESS);
    CHECK(got == 500 + (1 - rank));
    CHECK(MPI_Comm_disconnect(&joined) == MPI_SUCCESS);
  }

  /* ---- general dist_graph: rank 0 declares the whole ring ---- */
  {
    MPI_Comm ring = MPI_COMM_NULL;
    int *src = NULL, *deg = NULL, *dst = NULL;
    int n = 0;
    if (rank == 0) {
      /* edges r -> (r+1)%size for every r, all declared by rank 0 */
      n = size;
      src = malloc(sizeof(int) * (size_t)size);
      deg = malloc(sizeof(int) * (size_t)size);
      dst = malloc(sizeof(int) * (size_t)size);
      for (int r = 0; r < size; r++) {
        src[r] = r;
        deg[r] = 1;
        dst[r] = (r + 1) % size;
      }
    }
    CHECK(MPI_Dist_graph_create(MPI_COMM_WORLD, n, src, deg, dst,
                                MPI_UNWEIGHTED, MPI_INFO_NULL, 0,
                                &ring) == MPI_SUCCESS);
    int indeg = -1, outdeg = -1, wflag = -1;
    CHECK(MPI_Dist_graph_neighbors_count(ring, &indeg, &outdeg,
                                         &wflag) == MPI_SUCCESS);
    CHECK(indeg == 1 && outdeg == 1 && wflag == 0);
    int in1 = -1, out1 = -1;
    CHECK(MPI_Dist_graph_neighbors(ring, 1, &in1, MPI_UNWEIGHTED, 1,
                                   &out1, MPI_UNWEIGHTED) ==
          MPI_SUCCESS);
    CHECK(in1 == (rank + size - 1) % size && out1 == (rank + 1) % size);
    /* the directed exchange actually routes */
    long sbuf = 9000 + rank, rbuf = -1;
    CHECK(MPI_Neighbor_alltoall(&sbuf, 1, MPI_LONG, &rbuf, 1, MPI_LONG,
                                ring) == MPI_SUCCESS);
    CHECK(rbuf == 9000 + (rank + size - 1) % size);
    MPI_Comm_free(&ring);
    free(src);
    free(deg);
    free(dst);
  }

  /* ---- predefined attr callbacks: DUP_FN propagates on dup ---- */
  {
    int kv = MPI_KEYVAL_INVALID;
    CHECK(MPI_Comm_create_keyval(MPI_COMM_DUP_FN,
                                 MPI_COMM_NULL_DELETE_FN, &kv, NULL) ==
          MPI_SUCCESS);
    CHECK(MPI_Comm_set_attr(MPI_COMM_WORLD, kv, (void *)0xFEED) ==
          MPI_SUCCESS);
    MPI_Comm dup;
    CHECK(MPI_Comm_dup(MPI_COMM_WORLD, &dup) == MPI_SUCCESS);
    void *got = NULL;
    int found = 0;
    CHECK(MPI_Comm_get_attr(dup, kv, &got, &found) == MPI_SUCCESS);
    CHECK(found == 1 && got == (void *)0xFEED);
    MPI_Comm_free(&dup);
    CHECK(MPI_Comm_delete_attr(MPI_COMM_WORLD, kv) == MPI_SUCCESS);
    CHECK(MPI_Comm_free_keyval(&kv) == MPI_SUCCESS);
  }

  MPI_Comm_free(&side);
  MPI_Barrier(MPI_COMM_WORLD);
  if (rank == 0) printf("ports_c OK on %d ranks\n", size);
  MPI_Finalize();
  return 0;
}
