"""Device-plane fault tolerance: the liveness probe (killable child +
armed guard), typed cause="device" classification into the SAME
FailureState the host plane feeds, the wedge-injection mode, the
survivor-mesh remesh, and the thread-plane recovery drill.

The host-plane FT pipeline watches PROCESSES; a TPU participant that
wedges mid-psum surfaces as an indefinite XLA hang.  These tests drive
the other half: probe → classify → flood → shrink → remesh → resume.
"""

import os
import threading
import time

import numpy as np
import pytest

from zhpe_ompi_tpu import ops
from zhpe_ompi_tpu.coll import tpu as coll_tpu
from zhpe_ompi_tpu.core import errhandler as errh
from zhpe_ompi_tpu.core import errors
from zhpe_ompi_tpu.ft import ulfm
from zhpe_ompi_tpu.ft.inject import FaultPlan, WedgedDevice
from zhpe_ompi_tpu.parallel import mesh as mesh_mod
from zhpe_ompi_tpu.runtime import flightrec, spc
from zhpe_ompi_tpu.runtime.checkpoint import Checkpointer
from zhpe_ompi_tpu.utils import deadline as deadline_mod

from test_ulfm import run_tcp_ft


def _stub_probe(kind="deadline", detail="stub", calls=None):
    """A probe_fn stub: the classification ladder without subprocess
    cost.  Counts the same SPC counters the real probe does, so the
    gated assertions hold either way."""

    def probe(timeout=None, deadline=None):
        if calls is not None:
            calls.append(kind)
        spc.record("device_probe_rounds")
        if kind in ("hung", "deadline"):
            spc.record("device_probe_misses")
        return kind, detail

    return probe


def _wedge_probe(wedge, miss_kind="deadline"):
    """A probe_fn stub keyed to ONE rank's wedge — the in-process model
    of reality: the killable child hangs only on the rank whose device
    wedged; every healthy rank's probe answers ok (its guard may expire
    while it waits out a PEER's wedge inside a collective — an ok probe
    must ride that out, never self-classify)."""

    def probe(timeout=None, deadline=None):
        spc.record("device_probe_rounds")
        if wedge.fired:
            spc.record("device_probe_misses")
            return miss_kind, "wedged participant"
        return "ok", '{"n": 1, "platform": "stub"}'

    return probe


class TestDeviceFaultType:
    def test_typed_class_and_family(self):
        e = errors.DeviceFault("wedged", failed_ranks=[2], kind="hung")
        assert e.errclass == errors.ERR_DEVICE_FAULT
        assert isinstance(e, errors.ProcFailed)  # recovery family
        assert e.failed_ranks == (2,) and e.kind == "hung"
        assert "DEVICE_FAULT" in errors.error_string(
            errors.ERR_DEVICE_FAULT)


class TestClassify:
    def test_miss_classifies_device_cause_into_failure_state(self):
        state = ulfm.FailureState(4)
        before = spc.read("device_faults")
        faults = []
        probe = mesh_mod.DeviceLivenessProbe(
            state=state, rank=2, on_fault=faults.append, enable=True)
        fault = probe.classify("deadline", "probe hit its deadline")
        assert isinstance(fault, errors.DeviceFault)
        assert state.is_failed(2)
        assert state.cause_of(2) == "device"
        assert faults == [fault]
        assert spc.read("device_faults") - before == 1
        # never a detector false positive: the cause is typed, not a
        # suspicion — the session gate proves the complement
        assert ulfm.false_positive_count() == 0

    def test_flightrec_event_is_typed(self):
        state = ulfm.FailureState(2)
        probe = mesh_mod.DeviceLivenessProbe(state=state, rank=1,
                                             enable=True)
        flightrec.arm()
        try:
            probe.classify("hung", "outer kill")
            window = flightrec.window()
        finally:
            flightrec.disarm()
        kinds = [e["type"] for e in window]
        assert flightrec.DEVICE_FAULT in kinds
        evt = [e for e in window
               if e["type"] == flightrec.DEVICE_FAULT][-1]
        assert evt["rank"] == 1 and evt["kind"] == "hung"
        # the FailureState classification event landed too (the same
        # FT_CLASS seam every other cause rides)
        assert flightrec.FT_CLASS in kinds


class TestGuard:
    def test_fast_region_no_probe_no_fault(self):
        calls = []
        probe = mesh_mod.DeviceLivenessProbe(
            state=ulfm.FailureState(2), rank=0, enable=True,
            probe_fn=_stub_probe(calls=calls), deadline=5.0)
        with probe.guard():
            pass
        assert calls == [] and probe.fault is None
        assert deadline_mod.live_watchdog_threads() == []

    def test_wedged_region_probes_and_classifies(self):
        state = ulfm.FailureState(2)
        release = threading.Event()
        probe = mesh_mod.DeviceLivenessProbe(
            state=state, rank=0, enable=True,
            probe_fn=_stub_probe("deadline"), deadline=0.05,
            on_fault=lambda f: release.set())
        with probe.guard():
            # the "wedged collective": parked until classification
            assert release.wait(10.0), "guard never classified"
        assert state.cause_of(0) == "device"
        assert probe.fault is not None and probe.fault.kind == "deadline"
        assert deadline_mod.live_watchdog_threads() == []

    def test_ok_probes_never_classify_a_slow_region(self):
        """A slow-but-alive local plane is a PEER's fault to classify:
        ok probes ride out the grace rounds and go quiet."""
        state = ulfm.FailureState(2)
        calls = []
        probe = mesh_mod.DeviceLivenessProbe(
            state=state, rank=0, enable=True,
            probe_fn=_stub_probe("ok", calls=calls), deadline=0.05,
            grace=2)
        hold = threading.Event()
        with probe.guard():
            deadline = time.monotonic() + 10.0
            while len(calls) < 2 and time.monotonic() < deadline:
                hold.wait(0.02)
        assert len(calls) >= 2
        assert probe.fault is None
        assert not state.is_failed(0)
        assert ulfm.false_positive_count() == 0

    def test_disabled_guard_is_a_noop(self):
        calls = []
        probe = mesh_mod.DeviceLivenessProbe(
            state=ulfm.FailureState(2), rank=0, enable=False,
            probe_fn=_stub_probe(calls=calls), deadline=0.01)
        with probe.guard():
            time.sleep(0.1)
        assert calls == [] and probe.fault is None

    def test_region_finishing_during_probe_is_not_classified(self):
        """The race the disarm re-check exists for: the collective
        completes while the probe child runs — no fault, no false
        positive."""
        state = ulfm.FailureState(2)
        probing = threading.Event()
        finish = threading.Event()

        def slow_probe(timeout=None, deadline=None):
            probing.set()
            finish.wait(10.0)  # the region exits while we "probe"
            return "deadline", "late miss"

        probe = mesh_mod.DeviceLivenessProbe(
            state=state, rank=0, enable=True, probe_fn=slow_probe,
            deadline=0.05)
        wd = probe.guard()
        wd.arm()
        assert probing.wait(10.0)
        # the region completes while the probe is still in flight:
        # signal the disarm first (white-box: avoid blocking this
        # thread on the watchdog's join while the probe still runs)
        wd._disarmed.set()
        finish.set()
        wd._thread.join(5.0)
        assert not wd._thread.is_alive()
        assert probe.fault is None
        assert not state.is_failed(0)


class TestProbeChild:
    """The REAL killable-child probe (one subprocess each — the
    moderately slow half; the ladder above is stubbed)."""

    def test_healthy_plane_answers_ok(self):
        kind, detail = mesh_mod.probe_device_plane(timeout=90.0,
                                                   deadline=60.0)
        assert kind == "ok", detail
        import json

        info = json.loads(detail)
        assert info["n"] >= 1
        assert info["platform"] == "cpu"
        assert deadline_mod.orphaned_probe_processes() == []

    def test_wedge_hook_is_scoped_to_the_wedged_rank(self, monkeypatch):
        """A shared-process job: rank 2's wedge must not hang a HEALTHY
        rank's probe child (the self-false-positive the rank-scoped
        hook exists to prevent) — rank 0's probe answers ok while the
        hook names rank 2; rank 2's own probe wedges."""
        monkeypatch.setenv(coll_tpu.WEDGE_ENV, "2")
        kind, detail = mesh_mod.probe_device_plane(
            timeout=60.0, deadline=30.0, rank=0)
        assert kind == "ok", (kind, detail)
        kind, _ = mesh_mod.probe_device_plane(
            timeout=60.0, deadline=6.0, rank=2)
        assert kind == "deadline", kind
        assert deadline_mod.orphaned_probe_processes() == []

    def test_wedged_plane_dies_at_its_internal_deadline(self):
        """The injected wedge (coll/tpu.WEDGE_ENV) hangs the child
        INSIDE the collective region; the internal watchdog kills it
        from the inside — the structured "deadline" outcome, never an
        indefinite XLA hang."""
        env = dict(os.environ)
        env[coll_tpu.WEDGE_ENV] = coll_tpu.WEDGE_ALL
        before = spc.read("device_probe_misses")
        kind, detail = mesh_mod.probe_device_plane(
            timeout=60.0, deadline=8.0, env=env)
        assert kind == "deadline", (kind, detail)
        assert spc.read("device_probe_misses") - before == 1
        assert deadline_mod.orphaned_probe_processes() == []


class TestWedgePlan:
    def test_wedge_composes_with_kill_plans(self):
        plan = FaultPlan(seed=5).kill_ranks([1, 2], after_ops=3) \
            .wedge_device(3, after_steps=2)
        assert plan.victims == frozenset({1, 2})
        assert plan.device_victims == frozenset({3})
        assert plan.kill_for(3) is None  # planes stay independent
        assert plan.wedge_for(1) is None
        assert plan.wedge_for(3) == 2

    def test_wedge_validation(self):
        with pytest.raises(errors.ArgError):
            FaultPlan().wedge_device(0, after_steps=-1)

    def test_unscheduled_rank_never_fires(self):
        plan = FaultPlan().wedge_device(1, after_steps=0)
        wedge = plan.arm_device(0)  # rank 0 has no wedge
        for _ in range(10):
            wedge.tick()
        assert not wedge.fired

    def test_fire_parks_until_release_then_raises_typed(self):
        state = ulfm.FailureState(4)
        wedge = WedgedDevice(2, after_steps=1, state=state)
        out = {}

        def victim():
            try:
                wedge.tick()   # step 1: survives
                wedge.tick()   # step 2: fires — parks here
            except errors.DeviceFault as e:
                out["fault"] = e

        t = threading.Thread(target=victim, daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while not wedge.fired and time.monotonic() < deadline:
            time.sleep(0.01)
        assert wedge.fired and "fault" not in out  # parked, not raised
        # the hook is SCOPED to the wedged rank's probes (a healthy
        # rank sharing the process keeps getting healthy answers)
        assert os.environ.get(coll_tpu.WEDGE_ENV) == "2"
        wedge.release(errors.DeviceFault("classified",
                                         failed_ranks=[2]))
        t.join(5.0)
        assert not t.is_alive()
        assert out["fault"].failed_ranks == (2,)
        assert os.environ.get(coll_tpu.WEDGE_ENV) is None

    def test_hold_wedge_ignores_release(self):
        wedge = WedgedDevice(1, after_steps=0, hold=True)
        unwound = threading.Event()

        def victim():
            try:
                wedge.tick()
            except errors.DeviceFault:
                unwound.set()

        t = threading.Thread(target=victim, daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while not wedge.fired and time.monotonic() < deadline:
            time.sleep(0.01)
        wedge.release()
        assert not unwound.wait(0.3), \
            "a hold wedge must stay parked (only SIGKILL ends it)"
        # the parked daemon thread is the process-death analog; clear
        # the wedge hook it exported so later probes in this test
        # session answer again
        os.environ.pop(coll_tpu.WEDGE_ENV, None)


class TestSurvivorMesh:
    def test_drops_failed_indices(self):
        m = mesh_mod.world_mesh()
        n = m.devices.size
        surv = mesh_mod.survivor_mesh(m, failed=[1, n - 1])
        assert surv.devices.size == n - 2
        kept = set(np.asarray(surv.devices).flat)
        flat = list(np.asarray(m.devices).flat)
        assert not (kept & {flat[1], flat[n - 1]})
        assert surv.axis_names == m.axis_names

    def test_multiaxis_drops_along_named_axis(self):
        m = mesh_mod.make_mesh({"dp": 4, "tp": 2})
        surv = mesh_mod.survivor_mesh(m, failed=[2], axis="dp")
        assert surv.shape["dp"] == 3 and surv.shape["tp"] == 2

    def test_empty_survivor_set_raises(self):
        m = mesh_mod.make_mesh({"dp": 2, "tp": 4})
        with pytest.raises(errors.ArgError):
            mesh_mod.survivor_mesh(m, failed=[0, 1], axis="dp")
        with pytest.raises(errors.ArgError):
            mesh_mod.survivor_mesh(m, failed=[], axis="nope")


def _train_setup(rank: int, dim: int = 8) -> np.ndarray:
    """Deterministic per-rank fixed batch target."""
    r = np.random.default_rng(100 + rank)
    return r.normal(size=dim).astype(np.float32)


def _local_grad(w: np.ndarray, target: np.ndarray):
    loss = float(np.mean((w - target) ** 2))
    grad = ((2.0 / w.size) * (w - target)).astype(np.float32)
    return loss, grad


def _rebuild_full(zopt, leaves):
    """Rebuild a full-state pytree from its leaves (run_tcp_ft results
    cross threads as plain values; the treedef is the optimizer's)."""
    import jax

    treedef = jax.tree_util.tree_structure(zopt._opt_state)
    return jax.tree_util.tree_unflatten(treedef, leaves)


class TestZeroReshard:
    """ZeroOptimizer.full_state()/reshard(): optimizer chunks gather to
    every rank and re-shard onto a different-size endpoint with the
    training trajectory preserved."""

    def test_full_state_gathers_and_reshards_across_sizes(self):
        import jax
        import optax

        from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

        n, dim = 3, 10
        params = {"w": np.arange(dim, dtype=np.float32)}
        grads = {"w": np.ones(dim, np.float32)}

        def prog(p):
            zopt = ZeroOptimizer(p, optax.adam(1e-2), params)
            p1 = zopt.step(params, grads)
            full = zopt.full_state()
            zopt.reshard(p, full)  # same-size identity round trip
            p2 = zopt.step(p1, grads)
            return (np.asarray(p2["w"]),
                    [np.asarray(x) for x in
                     jax.tree_util.tree_leaves(full)])

        res = run_tcp_ft(n, prog)
        for r in range(1, n):
            np.testing.assert_allclose(res[r][0], res[0][0], rtol=1e-6)
            for a, b in zip(res[r][1], res[0][1]):
                np.testing.assert_allclose(a, b, rtol=1e-6)
        # reference: a SIZE-1 endpoint adopting the distributed full
        # state after one step produces the same second step (grads
        # are identical on every rank, so the distributed mean equals
        # the single-rank gradient)
        class P1:
            rank, size = 0, 1

        zr = ZeroOptimizer(P1(), optax.adam(1e-2), params, weight=1.0)
        q1 = zr.step(params, grads)
        zr.reshard(P1(), _rebuild_full(zr, res[0][1]))
        q2 = zr.step(q1, grads)
        np.testing.assert_allclose(np.asarray(q2["w"]), res[0][0],
                                   rtol=1e-5)


class TestDeviceWedgeRecoveryThreadPlane:
    """The in-process drill: a 4-rank ft job hits a wedged device
    participant mid-training — typed cause="device" classification
    (the wedged rank's own guard), notice flood to every survivor,
    consensus shrink, checkpoint rollback, optimizer re-shard onto the
    survivor endpoint, and SHRUNKEN training that matches the
    fault-free reference arithmetic.  No detector false positive
    anywhere (the session gate re-proves it suite-wide)."""

    N = 4
    VICTIM = 2
    WEDGE_AT = 2  # completes 2 steps, wedges entering step 3
    STEPS = 6
    DIM = 8

    def _reference_losses(self, phases, w0, probe_rank):
        """Fault-free single-process reference: the same arithmetic
        the distributed loop runs — per-step update from the MEAN
        gradient over the phase's rank set (what reduce-scatter of the
        1/n-weighted blocks computes), with the rank set switching
        between phases exactly where the shrink lands.  Returns
        ``probe_rank``'s LOCAL loss trajectory (what that rank's loop
        records) and the final params."""
        import optax

        from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

        class P1:
            rank, size = 0, 1

        zopt = ZeroOptimizer(P1(), optax.adam(1e-2), {"w": w0},
                             weight=1.0)
        params = {"w": w0.copy()}
        probe_target = _train_setup(probe_rank, self.DIM)
        losses = []
        for ranks, steps in phases:
            targets = [_train_setup(r, self.DIM) for r in ranks]
            for _ in range(steps):
                losses.append(_local_grad(params["w"],
                                          probe_target)[0])
                grad = np.mean(
                    [_local_grad(params["w"], t)[1] for t in targets],
                    axis=0).astype(np.float32)
                params = zopt.step(params, {"w": grad})
        return losses, np.asarray(params["w"])

    def test_wedge_classify_flood_shrink_rollback_reshard(
            self, fresh_vars, tmp_path):
        import optax

        from zhpe_ompi_tpu.mca import var as mca_var
        from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer

        mca_var.set_var("ft_detector_period", 0.05)
        # the heartbeat window is HUGE: the wedged rank keeps beating
        # (device wedge, not process death) — only the device probe
        # can classify this failure mode
        mca_var.set_var("ft_detector_timeout", 60.0)
        n, victim = self.N, self.VICTIM
        plan = FaultPlan(seed=9).wedge_device(victim,
                                              after_steps=self.WEDGE_AT)
        w0 = np.zeros(self.DIM, np.float32)
        faults0 = spc.read("device_faults")

        def prog(p):
            from zhpe_ompi_tpu.coll import host as coll_host

            p.set_errhandler(errh.ERRORS_RETURN)
            target = _train_setup(p.rank, self.DIM)
            ck = Checkpointer(str(tmp_path / f"r{p.rank}"), keep=10,
                              check_quiescent=False)
            zopt = ZeroOptimizer(p, optax.adam(1e-2), {"w": w0})
            wedge = plan.arm_device(p.rank, state=p.ft_state)
            probe = mesh_mod.DeviceLivenessProbe(
                state=p.ft_state, rank=p.rank, enable=True,
                probe_fn=_wedge_probe(wedge), deadline=0.3)
            probe.on_fault = lambda f: (p.flood_device_fault(f),
                                        wedge.release(f))
            params = {"w": w0.copy()}
            losses = []
            step = 0
            try:
                while step < self.STEPS:
                    with probe.guard():
                        wedge.tick()
                        loss, grad = _local_grad(params["w"], target)
                        params = zopt.step(params, {"w": grad})
                    step += 1
                    losses.append(loss)
                    ck.save(step, {"params": params,
                                   "opt": zopt.full_state()},
                            blocking=True)
                return ("clean", losses)
            except errors.DeviceFault as e:
                assert p.rank in e.failed_ranks
                return ("wedged", step)
            except (errors.ProcFailed, errors.ProcFailedPending,
                    errors.Revoked):
                # unblock the peers still parked in the collective
                p.revoke(coll_host.COLL_CID)
                assert p.ft_state.wait_failed(victim, timeout=10.0)
                # the transport symptom may win the classification
                # race (the wedged rank's sm teardown mid-send); the
                # typed device pair refines it when the flood lands
                deadline = time.monotonic() + 10.0
                while p.ft_state.cause_of(victim) != "device" \
                        and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert p.ft_state.cause_of(victim) == "device", \
                    p.ft_state.cause_of(victim)
                p.failure_ack()
                sh = p.shrink()
                # ROLLBACK + REMESH: restore the last quiescent
                # snapshot and re-shard the optimizer partition onto
                # the survivor endpoint
                snap, ck_step = ck.restore()
                params = {"w": np.asarray(snap["params"]["w"])}
                zopt.reshard(sh, snap["opt"])
                del losses[ck_step:]
                step = ck_step
                while step < self.STEPS:
                    loss, grad = _local_grad(params["w"], target)
                    params = zopt.step(params, {"w": grad})
                    step += 1
                    losses.append(loss)
                # synchronize before close: a fast survivor's goodbye
                # must not poison a peer's trailing reduce_scatter
                sh.barrier()
                return ("survivor", losses, np.asarray(params["w"]))

        res = run_tcp_ft(n, prog)
        assert res[victim][0] == "wedged"
        survivors = [r for r in range(n) if r != victim]
        for r in survivors:
            assert res[r][0] == "survivor", res[r]
        for r in survivors[1:]:
            np.testing.assert_allclose(res[r][2], res[survivors[0]][2],
                                       rtol=1e-6)
        # the post-recovery trajectory equals the fault-free reference:
        # 2 full-size steps, rollback to the step-2 snapshot, then 4
        # survivor-size steps — the "correct post-recovery loss" gate
        ref_losses, ref_w = self._reference_losses(
            [(list(range(n)), self.WEDGE_AT),
             (survivors, self.STEPS - self.WEDGE_AT)], w0,
            probe_rank=survivors[0])
        np.testing.assert_allclose(res[survivors[0]][1], ref_losses,
                                   rtol=1e-4)
        np.testing.assert_allclose(res[survivors[0]][2], ref_w,
                                   rtol=1e-4)
        # exactly ONE device classification: the victim's own guard
        # (survivors learned through the typed notice flood)
        assert spc.read("device_faults") - faults0 == 1

    def test_mixed_host_and_device_storm(self, fresh_vars):
        """One plan, both planes: a host-plane kill AND a device wedge
        in the same job — every survivor classifies both corpses with
        their own typed causes and one shrink absorbs both."""
        from zhpe_ompi_tpu.mca import var as mca_var

        mca_var.set_var("ft_detector_period", 0.05)
        mca_var.set_var("ft_detector_timeout", 60.0)
        n, kill_victim, wedge_victim = 4, 1, 3
        plan = FaultPlan(seed=11) \
            .kill_rank(kill_victim, after_ops=0) \
            .wedge_device(wedge_victim, after_steps=0)
        assert plan.victims == frozenset({kill_victim})
        assert plan.device_victims == frozenset({wedge_victim})

        def prog(p):
            from zhpe_ompi_tpu.coll import host as coll_host

            p.set_errhandler(errh.ERRORS_RETURN)
            wedge = plan.arm_device(p.rank, state=p.ft_state)
            probe = mesh_mod.DeviceLivenessProbe(
                state=p.ft_state, rank=p.rank, enable=True,
                probe_fn=_wedge_probe(wedge, "hung"), deadline=0.3)
            probe.on_fault = lambda f: (p.flood_device_fault(f),
                                        wedge.release(f))
            inj = plan.arm(p)
            try:
                with probe.guard():
                    wedge.tick()
                    # the host-plane victim dies inside this collective
                    inj.allreduce(np.full(8, float(p.rank + 1)),
                                  ops.SUM)
            except errors.DeviceFault as e:
                assert p.rank in e.failed_ranks
                return "wedged"
            except (errors.ProcFailed, errors.ProcFailedPending,
                    errors.Revoked):
                p.revoke(coll_host.COLL_CID)
            assert p.ft_state.wait_failed(kill_victim, timeout=10.0)
            assert p.ft_state.wait_failed(wedge_victim, timeout=10.0)
            deadline = time.monotonic() + 10.0
            while p.ft_state.cause_of(wedge_victim) != "device" \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert p.ft_state.cause_of(wedge_victim) == "device"
            p.failure_ack()
            sh = p.shrink()
            total = sh.allreduce(np.full(4, 1.0), ops.SUM)
            return (sh.size, float(np.asarray(total)[0]))

        res = run_tcp_ft(n, prog)
        assert res[kill_victim] == "killed"
        assert res[wedge_victim] == "wedged"
        survivors = [r for r in range(n)
                     if r not in (kill_victim, wedge_victim)]
        for r in survivors:
            assert res[r] == (2, 2.0), res[r]


class TestFtTrainLoop:
    """models/ftloop.FtTrainLoop plumbing that needs no fault: the
    guarded step loop, checkpoint cadence, and the restore path a
    replacement takes (the slow DVM drill exercises the full
    recovery)."""

    def _proc_stub(self):
        class Stub:
            rank, size = 0, 1
            ft_state = ulfm.FailureState(1)
        return Stub()

    @staticmethod
    def _step_fn(target):
        def step_fn(ep, state, i):
            loss, grad = _local_grad(state["w"], target)
            return {"w": state["w"] - 0.1 * grad}, loss
        return step_fn

    def test_runs_steps_and_checkpoints(self, tmp_path):
        from zhpe_ompi_tpu.models.ftloop import FtTrainLoop

        loop = FtTrainLoop(
            self._proc_stub(), step_fn=self._step_fn(_train_setup(0)),
            state={"w": np.zeros(8, np.float32)},
            checkpointer=Checkpointer(str(tmp_path), keep=10,
                                      check_quiescent=False),
            ckpt_every=2)
        state, losses = loop.run(5)
        assert len(losses) == 5
        assert losses[-1] < losses[0]  # it learns
        # step-0 snapshot + every-2 cadence + the final step
        assert loop.ckpt.all_steps() == [0, 2, 4, 5]

    def test_restore_resumes_the_exact_trajectory(self, tmp_path):
        from zhpe_ompi_tpu.models.ftloop import FtTrainLoop

        step_fn = self._step_fn(_train_setup(0))
        ck = Checkpointer(str(tmp_path), keep=20,
                          check_quiescent=False)
        first = FtTrainLoop(self._proc_stub(), step_fn=step_fn,
                            state={"w": np.zeros(8, np.float32)},
                            checkpointer=ck, ckpt_every=1)
        first.run(8)
        full_losses = list(first.losses)
        # a "replacement" restores the step-6 snapshot and continues:
        # its trailing losses must equal the unbroken run's
        second = FtTrainLoop(self._proc_stub(), step_fn=step_fn,
                             state={"w": np.zeros(8, np.float32)},
                             checkpointer=ck, ckpt_every=1)
        second.restore(None)  # latest is step 8; pick 6 explicitly
        second.state, step = ck.restore(6)
        second.step_i = step
        second.run(8)
        np.testing.assert_allclose(second.losses, full_losses[6:8],
                                   rtol=1e-6)

    def test_rejoin_restore_threads_shardings_fn(self, tmp_path,
                                                 monkeypatch):
        """The device-plane restore leg: a replacement's (and the
        rollback's) checkpoint restore passes shardings_fn(ep) through
        to Checkpointer.restore, so sharded state materializes directly
        onto the endpoint's mesh instead of staging on the host."""
        from zhpe_ompi_tpu.models.ftloop import FtTrainLoop

        step_fn = self._step_fn(_train_setup(0))
        ck = Checkpointer(str(tmp_path), check_quiescent=False)
        FtTrainLoop(self._proc_stub(), step_fn=step_fn,
                    state={"w": np.zeros(8, np.float32)},
                    checkpointer=ck, ckpt_every=1).run(2)
        seen = []
        orig = ck.restore

        def spying_restore(step=None, shardings=None):
            seen.append(shardings)
            return orig(step, shardings)

        ck.restore = spying_restore
        monkeypatch.setenv("ZMPI_REJOIN", "1")
        loop = FtTrainLoop(
            self._proc_stub(), step_fn=step_fn,
            state={"w": np.zeros(8, np.float32)}, checkpointer=ck,
            ckpt_every=1,
            shardings_fn=lambda ep: {"w": None})
        loop.run(2)
        assert seen == [{"w": None}]  # the hook's tree reached restore
        assert loop.step_i == 2

    def test_typed_fault_without_respawner_is_loud(self, tmp_path):
        from zhpe_ompi_tpu.models.ftloop import FtTrainLoop

        def step_fn(ep, state, i):
            raise errors.ProcFailed("peer died", failed_ranks=[1])

        loop = FtTrainLoop(
            self._proc_stub(), step_fn=step_fn, state={"x": 1},
            checkpointer=Checkpointer(str(tmp_path),
                                      check_quiescent=False))
        with pytest.raises(errors.UnsupportedError):
            loop.run(1)

    def test_own_device_fault_reraises(self, tmp_path):
        from zhpe_ompi_tpu.models.ftloop import FtTrainLoop

        def step_fn(ep, state, i):
            raise errors.DeviceFault("me", failed_ranks=[0])

        loop = FtTrainLoop(
            self._proc_stub(), step_fn=step_fn, state={"x": 1},
            checkpointer=Checkpointer(str(tmp_path),
                                      check_quiescent=False),
            respawner=lambda victims: None)
        with pytest.raises(errors.DeviceFault):
            loop.run(1)


_DVM_DEVICE_DRILL_PROG = '''
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import optax
import zhpe_ompi_tpu as zmpi
from zhpe_ompi_tpu.core import errhandler as errh
from zhpe_ompi_tpu.ft import recovery
from zhpe_ompi_tpu.ft.inject import FaultPlan
from zhpe_ompi_tpu.models.ftloop import FtTrainLoop
from zhpe_ompi_tpu.parallel import mesh as mesh_mod
from zhpe_ompi_tpu.parallel.zero import ZeroOptimizer
from zhpe_ompi_tpu.runtime.checkpoint import Checkpointer

DIM = 8
STEPS = 6
WEDGE_RANK = int(os.environ.get("TEST_WEDGE_RANK", "-1"))
WEDGE_AT = int(os.environ.get("TEST_WEDGE_AT", "2"))

proc = zmpi.host_init()
proc.set_errhandler(errh.ERRORS_RETURN)

rng = np.random.default_rng(100 + proc.rank)
target = rng.normal(size=DIM).astype(np.float32)
w0 = np.zeros(DIM, np.float32)
zopt = None  # bound to the loop's live window below


def step_fn(ep, state, i):
    w = np.asarray(state["params"]["w"], np.float32)
    loss = float(np.mean((w - target) ** 2))
    grad = ((2.0 / w.size) * (w - target)).astype(np.float32)
    params = zopt.step({{"w": w}}, {{"w": grad}})
    return {{"params": params, "opt": zopt.full_state()}}, loss


observed = {{}}


def remesh_fn(ep, state):
    # the survivor-mesh / full-size re-shard leg; also the spot where
    # the AGREED (refined) cause is known — sample it for the gate
    if state.get("opt") is not None:
        zopt.reshard(ep, state["opt"])
    else:
        zopt.proc = ep  # fresh moments, new window
    if WEDGE_RANK >= 0 and proc.rank != WEDGE_RANK:
        c = proc.ft_state.cause_of(WEDGE_RANK)
        if c:
            observed.setdefault("cause", c)


plan = FaultPlan(seed=3)
if WEDGE_RANK >= 0 and os.environ.get("ZMPI_REJOIN") != "1":
    # the wedge fires in the FIRST incarnation only: a respawned
    # replacement re-arming the same schedule would wedge itself at
    # the same step forever (observed: an endless respawn carousel)
    plan.wedge_device(WEDGE_RANK, after_steps=WEDGE_AT)
# hold=True: the victim process NEVER unwinds — healthy heartbeats,
# hung device — until the recovery respawn SIGKILLs it (the PRRTE
# declared-dead-incarnation contract; "never an XLA hang" means the
# JOB moves on, not that the wedge resolves)
wedge = plan.arm_device(proc.rank, state=proc.ft_state, hold=True)
probe = mesh_mod.DeviceLivenessProbe(
    state=proc.ft_state, rank=proc.rank, enable=True,
    timeout=float(os.environ.get("TEST_PROBE_TIMEOUT", "40")),
    deadline=float(os.environ.get("TEST_PROBE_DEADLINE", "8")))

loop = FtTrainLoop(
    proc, step_fn=step_fn,
    state={{"params": {{"w": w0.copy()}}, "opt": None}},
    checkpointer=Checkpointer(
        os.path.join(os.environ["TEST_CKPT"], f"r{{proc.rank}}"),
        keep=20, check_quiescent=False),
    ckpt_every=1, probe=probe, wedge=wedge,
    respawner=recovery.daemon_respawn, remesh_fn=remesh_fn)
# the optimizer's collectives ride the loop's LIVE window (the
# revocable, generation-isolated channel recovery depends on);
# remesh_fn re-binds it on every window change
zopt = ZeroOptimizer(loop.live, optax.adam(1e-2), {{"w": w0}})
state, losses = loop.run(STEPS)
print(f"TRAIN-OK rank={{proc.rank}} size={{proc.size}} "
      f"recoveries={{loop.recoveries}} steps={{len(losses)}} "
      f"final={{losses[-1]:.6f}} "
      f"cause={{observed.get('cause', '-')}}", flush=True)
zmpi.host_finalize()
'''


@pytest.mark.slow
class TestDeviceFaultTrainRecoveryDvm:
    """THE acceptance drill (ISSUE 14): a models/ train loop over a
    real-process ft DVM job survives an injected wedged-participant
    psum — typed cause="device" classification (never a detector false
    positive, never an XLA hang: the victim process stays parked until
    the respawn SIGKILLs it), consensus shrink, optimizer re-shard,
    checkpoint rollback, daemon respawn, resume at FULL size — and the
    post-recovery losses equal the fault-free run's, rank for rank."""

    N = 3
    VICTIM = 1

    def _launch(self, tmp_path, wedge: bool):
        import io
        import re

        from zhpe_ompi_tpu.runtime import dvm as dvm_mod

        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        tag = "wedge" if wedge else "ref"
        prog = tmp_path / f"drill_{tag}.py"
        prog.write_text(_DVM_DEVICE_DRILL_PROG.format(repo=repo))
        env = {
            "TEST_CKPT": str(tmp_path / f"ckpt_{tag}"),
            "TEST_WEDGE_RANK": str(self.VICTIM) if wedge else "-1",
            "TEST_WEDGE_AT": "2",
            "TEST_PROBE_DEADLINE": "8",
            "TEST_PROBE_TIMEOUT": "40",
        }
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        d = dvm_mod.Dvm()
        try:
            cli = dvm_mod.DvmClient(d.address)
            out, err = io.StringIO(), io.StringIO()
            rc = cli.launch(
                self.N, [str(prog)], ft=True, timeout=240.0,
                # the heartbeat window is deliberately huge AND the
                # victim keeps beating: only the device probe can
                # classify this failure mode
                mca=[("ft_detector_period", "2.0"),
                     ("ft_detector_timeout", "120.0")],
                stdout=out, stderr=err,
            )
            text = out.getvalue()
            assert rc == 0, (text, err.getvalue())
            rows = re.findall(
                r"TRAIN-OK rank=(\d+) size=(\d+) recoveries=(\d+) "
                r"steps=(\d+) final=([\d.]+) cause=(\S+)", text)
            stat = cli.stat()
            cli.stop()
            cli.close()
            return rows, stat
        finally:
            d.stop()
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def test_train_loop_survives_wedged_participant(self, tmp_path):
        from zhpe_ompi_tpu.ft import ulfm as ulfm_mod
        from zhpe_ompi_tpu.runtime import dvm as dvm_mod
        from zhpe_ompi_tpu.runtime import spc as spc_mod

        fps0 = ulfm_mod.false_positive_count()
        before = spc_mod.snapshot()
        ref_rows, _ = self._launch(tmp_path, wedge=False)
        assert len(ref_rows) == self.N
        ref_final = {int(r): float(f)
                     for r, _, _, _, f, _ in ref_rows}
        assert all(int(rec) == 0 for _, _, rec, _, _, _ in ref_rows)

        rows, stat = self._launch(tmp_path, wedge=True)
        # every rank finished at FULL size: the survivors (one
        # recovery each) and the respawned replacement (zero — its
        # loop began at the rolled-back step)
        assert len(rows) == self.N, rows
        by_rank = {int(r): (int(s), int(rec), int(st), float(f), c)
                   for r, s, rec, st, f, c in rows}
        assert set(by_rank) == set(range(self.N))
        for r, (size, recoveries, steps, final, cause) in \
                by_rank.items():
            assert size == self.N
            if r == self.VICTIM:
                # the replacement: restored the rolled-back step-2
                # snapshot and ran the remaining 4 steps cleanly
                assert recoveries == 0
                assert steps == 4, steps
            else:
                assert recoveries == 1, (r, recoveries)
                assert steps == 6, steps
                # the typed classification, agreed at shrink: DEVICE —
                # never a detector suspicion, never a bare transport
                # symptom
                assert cause == "device", (r, cause)
        # the post-recovery loss is CORRECT: rank for rank, the wedged
        # run converged to the fault-free run's numbers
        for r in range(self.N):
            assert abs(by_rank[r][3] - ref_final[r]) <= 1e-4, (
                r, by_rank[r][3], ref_final[r])
        # one batched respawn; at least one authoritative daemon fault
        # event (the SIGKILLed wedged incarnation's waitpid)
        assert stat["dvm_respawns"] - before.get("dvm_respawns", 0) \
            == 1
        assert stat["pmix"] == {}
        # the device plane's own gates: probes ran, exactly one fault
        # classified, zero detector false positives
        after = spc_mod.snapshot()
        assert after.get("device_probe_rounds", 0) >= \
            before.get("device_probe_rounds", 0)
        assert ulfm_mod.false_positive_count() == fps0
        assert dvm_mod.live_dvms() == []
        assert dvm_mod.orphaned_daemon_processes() == []
