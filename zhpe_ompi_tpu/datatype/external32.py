"""external32 — MPI's canonical big-endian wire encoding.

Re-design of the convertor's heterogeneous path (reference:
``opal/datatype/opal_convertor.c`` arch-conversion flags, exercised by
``test/datatype/external32.c``): MPI_Pack_external / MPI_Unpack_external
serialize any datatype into the standard big-endian "external32"
representation so heterogeneous peers (and persisted files) interoperate
regardless of host endianness.

The hot path stays the native-order convertor (:mod:`.convertor`, with
its C++ kernels); external32 is the canonical-format slow path, exactly
the split the reference makes (homogeneous fast path vs. arch-convert
path).  Elements are emitted in typemap order, each byteswapped to big
endian; fixed-width IEEE numpy dtypes already match external32's type
sizes, so size == packed_size.
"""

from __future__ import annotations

import numpy as np

from ..core import errors
from .convertor import _as_byte_view, _check_lb, packed_size
from .predefined import Datatype


def _element_layout(datatype: Datatype, count: int):
    """(np_dtype, source_byte_offset) per element, canonical order —
    absolute displacements, matching the convertor's convention (elements
    of instance c live at c*extent + disp in the 0-based buffer)."""
    ext = datatype.extent
    _check_lb(datatype)
    out = []
    for c in range(count):
        for dt, disp in datatype.typemap():
            out.append((np.dtype(dt), c * ext + disp))
    return out


def pack_external(buffer, datatype: Datatype, count: int = 1) -> np.ndarray:
    """MPI_Pack_external("external32", ...): canonical big-endian bytes."""
    from .convertor import span_bytes

    src = _as_byte_view(buffer)
    need = span_bytes(datatype, count)
    if src.size < need:
        raise errors.TruncateError(
            f"buffer holds {src.size} bytes, need {need}"
        )
    parts = []
    for dt, off in _element_layout(datatype, count):
        raw = src[off : off + dt.itemsize].tobytes()
        be = np.frombuffer(raw, dtype=dt).astype(dt.newbyteorder(">"))
        parts.append(np.frombuffer(be.tobytes(), dtype=np.uint8))
    if not parts:
        return np.zeros(0, np.uint8)
    return np.concatenate(parts)


def unpack_external(packed, datatype: Datatype, count: int = 1,
                    out: np.ndarray | None = None) -> np.ndarray:
    """MPI_Unpack_external: canonical bytes back into a native buffer."""
    packed = np.asarray(packed, dtype=np.uint8).reshape(-1)
    expect = packed_size(datatype, count)
    if packed.size < expect:
        raise errors.TruncateError(
            f"packed stream holds {packed.size} bytes, need {expect}"
        )
    from .convertor import span_bytes

    need = span_bytes(datatype, count)
    if out is None:
        out = np.zeros(need, np.uint8)
        dst = out
    else:
        dst = _as_byte_view(out)
        if dst.size < need:
            raise errors.TruncateError("output buffer too small")
    pos = 0
    for dt, off in _element_layout(datatype, count):
        raw = packed[pos : pos + dt.itemsize].tobytes()
        native = np.frombuffer(
            raw, dtype=dt.newbyteorder(">")
        ).astype(dt)
        dst[off : off + dt.itemsize] = np.frombuffer(
            native.tobytes(), dtype=np.uint8
        )
        pos += dt.itemsize
    return out
