"""zprted — the persistent runtime daemon (PRRTE/DVM analog).

In the reference, ``mpirun`` is a symlink to the external ``prte`` binary
(``ompi/tools/mpirun/Makefile.am:11-15``): a *resident* runtime hosts the
PMIx server, launches jobs into itself, watches its children, and owns
fault notification — none of which lives in the MPI tree.  This module is
that daemon IN tree, the elastic-launcher / coordinator-service layer the
fault-tolerance planes of PRs 1–7 built toward:

- **resident PMIx store** (:mod:`.pmix`): one server outlives every job;
  ``zmpirun --dvm`` launches a job into the running VM and the ranks
  modex through the store — no per-job rendezvous coordinator, no name
  server, no launcher interpreter start-up (the launch-latency win the
  OSU ``--launch`` ladder gates).
- **authoritative fault events**: the daemon ``waitpid``-watches every
  child (one *blocking* ``wait()`` thread per proc — no polling in the
  hot path) and, the moment a rank of an ft job dies, floods an
  ``FT_DVM_CID`` control frame to every survivor.  That is OS truth —
  the corpse's exit status — feeding the same
  :class:`~zhpe_ompi_tpu.ft.ulfm.FailureState` as the ring heartbeats,
  marking the rank failed (``cause="daemon"``) before a single detector
  timeout expires.
- **relaunch RPC**: :func:`~zhpe_ompi_tpu.ft.recovery.daemon_respawn`
  asks the daemon to exec a fresh OS process into a dead rank's slot;
  the replacement FT_JOINs the name-served job (``TcpProc(rejoin=True)``
  fetches the book from the store), closing the recovery pipeline over
  real processes end to end.  One respawn RPC may carry N victims — the
  namespace generation is bumped ONCE, so the whole batch joins the
  same recovery window.

Wire protocol (control port; length-framed DSS, request/response with
streaming for ``launch``): requests are ``["launch", spec]``,
``["respawn", job, ranks]``, ``["pids", job]``, ``["stat"]``,
``["metrics", job[, rank]]``, ``["ping"]``, ``["stop"]``.  A launch
streams ``["job", id]``, then ``["io", rank, label, line]`` /
``["note", text]`` frames, and finally ``["exit", rc]``.

The daemon is also the metrics plane's aggregation point: ranks
launched with ``metrics=True`` (``ZMPI_METRICS=1``) publish
generation-tagged ``metrics:<job>:<rank>`` snapshots into the resident
store, the ``metrics`` RPC serves per-rank / per-job / job-aggregated
views with staleness stamps, and — off by default, ``--metrics-port``
to enable — an HTTP ``GET /metrics`` listener emits the whole store's
counter plane as Prometheus text exposition
(``zmpi_spc_<name>{job="...",rank="..."} value``), so the han/sm/wire/
FT counters the benches gate on are scrapeable from a live fleet.

Job semantics mirror ``zmpirun``: non-ft jobs keep MPI_Abort teardown
(first nonzero exit kills the rest); ft jobs keep running — death is an
event for the survivors' recovery pipeline, not a job teardown.

Hygiene is observable: every in-process daemon registers weakly
(:func:`live_dvms` must be empty once tests stop theirs), daemon
*processes* are found by cmdline scan (:func:`orphaned_daemon_processes`),
and a stopping daemon destroys its jobs' namespaces and sweeps their
``/dev/shm`` artifacts exactly as the ``zmpirun`` session sweep does.

CLI (the ``zprted`` entrypoint)::

    python -m zhpe_ompi_tpu.runtime.dvm [--host H] [--port P] [--pmix-port Q]

prints ``zprted ready dvm=H:P pmix=H:Q`` once both listeners are up, and
runs until SIGTERM/SIGINT or a ``stop`` RPC.
"""

from __future__ import annotations

import argparse
import itertools
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import weakref
from typing import Any

from ..core import errors
from ..mca import output as mca_output
from ..mca import var as mca_var
from . import pmix as pmix_mod
from . import spc

_stream = mca_output.open_stream("dvm")

mca_var.register(
    "dvm_job_timeout", 600.0,
    "Default wall-clock deadline (seconds) for a daemon-hosted job "
    "that did not pass its own timeout: a wedged rank set may not park "
    "a zprted launch handler forever",
    type=float,
)

_TERM_GRACE = 2.0  # seconds between SIGTERM and SIGKILL on teardown

_live_dvms: weakref.WeakSet = weakref.WeakSet()


def live_dvms() -> list[str]:
    """In-process daemons still listening — must be [] once tests stop
    theirs (a leaked daemon holds two ports and a PMIx store)."""
    return [
        f"dvm:{d.address[0]}:{d.address[1]}"
        for d in list(_live_dvms)
        if not d.stopped
    ]


def orphaned_daemon_processes() -> list[str]:
    """zprted processes still alive on this host (cmdline scan) — the
    session gate's view: no daemon subprocess may outlive the test that
    spawned it."""
    out = []
    try:
        pids = [p for p in os.listdir("/proc") if p.isdigit()]
    except OSError:
        return out  # no /proc: nothing to scan
    for pid in pids:
        if int(pid) == os.getpid():
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                args = [a.decode("utf-8", "replace")
                        for a in f.read().split(b"\0") if a]
        except OSError:
            continue  # raced an exit
        # match ACTUAL daemon invocations only ("python -m
        # zhpe_ompi_tpu.runtime.dvm ..." or a zprted binary) — a
        # substring match would flag any shell/pytest line that merely
        # MENTIONS zprted (e.g. running a test by its name)
        if any(a == "zhpe_ompi_tpu.runtime.dvm" for a in args) or (
                args and os.path.basename(args[0]) == "zprted"):
            out.append(f"pid {pid}: {' '.join(args)}")
    return out


_live_metrics_http: weakref.WeakSet = weakref.WeakSet()


def live_metrics_listeners() -> list[str]:
    """Metrics HTTP listeners still bound — must be [] once every
    daemon's stop() ran (the scrape endpoint dies with its daemon)."""
    return [
        f"metrics-http:{h.address[0]}:{h.address[1]}"
        for h in list(_live_metrics_http)
        if not h.closed
    ]


class MetricsHttpListener:
    """Minimal HTTP/1.0 server for ``GET /metrics``: one accept loop,
    one short-lived thread per request, Prometheus text exposition
    rendered by the owning daemon.  Deliberately tiny — no keep-alive,
    no routing beyond /metrics, request read bounded — because its
    whole contract is "a scraper can poll this port"."""

    def __init__(self, dvm: "Dvm", host: str, port: int):
        self._dvm = dvm
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            self._srv.bind((host, port))
        except OSError:
            self._srv.close()
            raise
        self._srv.listen(8)
        self.address: tuple[str, int] = self._srv.getsockname()
        self.closed = False
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()
        self._acceptor = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"dvm-metrics-http-{self.address[1]}",
        )
        self._acceptor.start()
        _live_metrics_http.add(self)

    def _accept_loop(self) -> None:
        while not self.closed:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            with self._lock:
                self._threads = [t for t in self._threads if t.is_alive()]
                t = threading.Thread(
                    target=self._serve, args=(conn,), daemon=True,
                    name=f"dvm-metrics-req-{self.address[1]}",
                )
                self._threads.append(t)
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            data = b""
            while b"\r\n\r\n" not in data and len(data) < 8192:
                chunk = conn.recv(1024)
                if not chunk:
                    return
                data += chunk
            line = data.split(b"\r\n", 1)[0].decode("ascii", "replace")
            parts = line.split()
            path = parts[1] if len(parts) >= 2 else ""
            if parts and parts[0] == "GET" \
                    and path.split("?", 1)[0] == "/metrics":
                body = self._dvm.prometheus().encode("utf-8")
                head = (
                    "HTTP/1.0 200 OK\r\n"
                    "Content-Type: text/plain; version=0.0.4; "
                    "charset=utf-8\r\n"
                    f"Content-Length: {len(body)}\r\n\r\n"
                )
            else:
                body = b"not found\n"
                head = ("HTTP/1.0 404 Not Found\r\n"
                        "Content-Type: text/plain\r\n"
                        f"Content-Length: {len(body)}\r\n\r\n")
            conn.sendall(head.encode("ascii") + body)
        except OSError:
            return  # scraper went away mid-request: its own problem
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        deadline = time.monotonic() + 5.0
        self._acceptor.join(max(0.0, deadline - time.monotonic()))
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))


def _sweep_shm(session: str) -> None:
    """Session-directory cleanup for one session tag (the zmpirun sweep,
    shared prefix scheme): killed ranks never unlink their rings."""
    try:
        for f in os.listdir("/dev/shm"):
            if f.startswith((f"zompi_ring_{session}_",
                             f"zompi_shm_{session}_",
                             f"zompi_pyring_{session}_")):
                try:
                    os.unlink(os.path.join("/dev/shm", f))
                except OSError:
                    pass
    except OSError:
        pass


class _Job:
    """One launched job: its procs (latest incarnation per rank), exit
    bookkeeping, and the IOF client connection."""

    def __init__(self, job_id: str, size: int, cmds: list[list[str]],
                 ft: bool, mca: list, session: str, conn, conn_lock,
                 metrics: bool = False, trace: bool = False):
        self.id = job_id
        self.size = size
        self.cmds = cmds
        self.ft = ft
        self.mca = mca
        self.metrics = metrics
        self.trace = trace
        self.session = session
        self.conn = conn              # IOF/exit stream target
        self.conn_lock = conn_lock
        self.lock = threading.Lock()
        self.procs: dict[int, subprocess.Popen] = {}
        self.rcs: dict[int, int] = {}
        self.superseded: dict[int, list[subprocess.Popen]] = {}
        self.live = 0
        self.fail_rc: int | None = None
        self.stopping = False
        self.io_broken = False
        self.done = threading.Event()
        self.drains: list[threading.Thread] = []

    def alive_ranks(self) -> list[int]:
        with self.lock:
            return sorted(r for r, p in self.procs.items()
                          if p.poll() is None)


class Dvm(pmix_mod.FramedRpcServer):
    """The resident daemon: PMIx store + control RPC + child watching.
    Constructible in-process (tests, benchmarks) or via the ``zprted``
    CLI as its own OS process.  The control port rides the shared
    framed-RPC scaffold (:class:`~zhpe_ompi_tpu.runtime.pmix.
    FramedRpcServer`); ``launch`` is the one streaming request —
    replies are emitted by the job machinery
    (``[job]``/``[io]``/``[note]``/``[exit]`` frames)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 pmix_port: int = 0, session_tag: str | None = None,
                 metrics_port: int | None = None):
        self.host = host
        self.store = pmix_mod.PmixStore()
        self.pmix = pmix_mod.PmixServer(host, pmix_port, store=self.store)
        self.metrics_http: MetricsHttpListener | None = None
        try:
            super().__init__(host, port, "dvm", backlog=16)
        except OSError:
            self.pmix.close()
            raise
        if metrics_port is not None:
            # scrape endpoint OFF by default: binding a port is an
            # explicit operator decision (--metrics-port)
            try:
                self.metrics_http = MetricsHttpListener(
                    self, host, int(metrics_port))
            except OSError:
                self.pmix.close()
                super().close()
                raise
        self.session = session_tag or f"d{self.address[1]}"
        self._stop_evt = threading.Event()
        self._jobs: dict[str, _Job] = {}
        self._job_ids = itertools.count(1)
        self._lock = threading.Lock()
        _live_dvms.add(self)
        mca_output.verbose(
            1, _stream, "zprted up: dvm=%s:%d pmix=%s:%d session=%s",
            host, self.address[1], host, self.pmix.address[1], self.session,
        )

    # -- wire ------------------------------------------------------------

    @property
    def stopped(self) -> bool:
        return self.closed

    def _handle_request(self, req: list, conn, conn_lock) -> Any:
        if req[0] == "launch":
            self._handle_launch(req[1], conn, conn_lock)
            return self.STREAMED
        return self._dispatch(req)

    def _after_reply(self, req: list) -> bool:
        if req[0] == "stop":
            self.stop()
            return False
        return True

    def _dispatch(self, req: list) -> Any:
        op = req[0]
        if op == "ping":
            return "pong"
        if op == "stat":
            with self._lock:
                jobs = {j.id: {"size": j.size, "ft": j.ft,
                               "live": len(j.alive_ranks()),
                               "done": j.done.is_set()}
                        for j in self._jobs.values()}
            counters = spc.snapshot()
            return {
                "jobs": jobs,
                "pmix": self.store.stat(),
                "dvm_jobs_launched": counters.get("dvm_jobs_launched", 0),
                "dvm_fault_events": counters.get("dvm_fault_events", 0),
                "dvm_respawns": counters.get("dvm_respawns", 0),
            }
        if op == "pids":
            job = self._job(req[1])
            with job.lock:
                return {int(r): p.pid for r, p in job.procs.items()}
        if op == "metrics":
            return self._metrics_view(
                str(req[1]), None if len(req) < 3 or req[2] is None
                else int(req[2]))
        if op == "respawn":
            return self._handle_respawn(req[1], [int(r) for r in req[2]])
        if op == "stop":
            return True
        raise errors.ArgError(f"zprted: unknown request {op!r}")

    def _job(self, job_id: str) -> _Job:
        with self._lock:
            job = self._jobs.get(job_id)
        if job is None:
            raise errors.ArgError(f"zprted: unknown job {job_id!r}")
        return job

    # -- metrics aggregation ----------------------------------------------

    def _metrics_ranks(self, ns: str) -> dict[int, dict]:
        """Per-rank published metrics of one namespace, staleness-
        stamped (``staleness_s``: daemon wall clock minus the
        snapshot's publish stamp), with each rank's flight-recorder
        window attached when one was published."""
        now = time.time()
        ranks: dict[int, dict] = {}
        for key, payload in self.store.lookup(ns, "metrics:").items():
            try:
                rank = int(key.rsplit(":", 1)[1])
                rec = dict(payload)
            except (ValueError, TypeError):
                continue  # foreign key shape: not a publisher's
            rec["staleness_s"] = max(0.0, now - float(rec.get("t", now)))
            ranks[rank] = rec
        for key, win in self.store.lookup(ns, "flightrec:").items():
            try:
                rank = int(key.rsplit(":", 1)[1])
            except ValueError:
                continue
            ranks.setdefault(rank, {})["flightrec"] = win
        return ranks

    def _metrics_view(self, ns: str, rank: int | None = None):
        """The ``metrics`` RPC: one rank's record, or the whole job —
        every rank's record plus the job-aggregated counter view
        (counters summed, watermarks maxed)."""
        ranks = self._metrics_ranks(ns)
        if not ranks:
            raise errors.ArgError(
                f"zprted metrics: no metrics published for job {ns!r} "
                "(launch with metrics=True / ZMPI_METRICS=1)")
        if rank is not None:
            if rank not in ranks:
                raise errors.ArgError(
                    f"zprted metrics: rank {rank} of job {ns!r} has "
                    "published nothing")
            return ranks[rank]
        aggregate: dict[str, int] = {}
        watermarks: set[str] = set()
        for rec in ranks.values():
            watermarks.update(rec.get("watermark") or ())
            for name, value in (rec.get("counters") or {}).items():
                if name in watermarks:
                    aggregate[name] = max(aggregate.get(name, 0), value)
                else:
                    aggregate[name] = aggregate.get(name, 0) + value
        return {"job": ns, "ranks": ranks, "aggregate": aggregate}

    @staticmethod
    def _prom_name(name: str) -> str:
        """Metric-name charset is [a-zA-Z0-9_:]; anything else (a
        templated family like ``comm_<name>_coll_calls`` instantiated
        with a dashed communicator name) collapses to ``_`` — one bad
        counter name must not invalidate the whole scrape body."""
        return re.sub(r"[^a-zA-Z0-9_:]", "_", name)

    @staticmethod
    def _prom_label(value: str) -> str:
        """Label-value escaping per the text exposition format
        (backslash, double-quote, newline)."""
        return (str(value).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    def prometheus(self) -> str:
        """Text exposition of every namespace's published snapshots:
        ``zmpi_spc_<counter>{job="...",rank="..."} value`` plus a
        staleness gauge per rank — the ``GET /metrics`` body.  Samples
        are grouped by METRIC family (one contiguous block after each
        TYPE line, the exposition format's rule), not by rank — strict
        OpenMetrics-mode scrapers reject interleaved families."""
        # metric -> (kind, [sample lines]); insertion builds the rows,
        # emission walks families sorted
        families: dict[str, tuple[str, list[str]]] = {}

        def sample(metric: str, kind: str, labels: str, value) -> None:
            fam = families.setdefault(metric, (kind, []))
            fam[1].append(f"{metric}{labels} {value}")

        for ns in self.store.namespaces():
            ranks = self._metrics_ranks(ns)
            for rank in sorted(ranks):
                rec = ranks[rank]
                counters = rec.get("counters") or {}
                watermarks = set(rec.get("watermark") or ())
                labels = (f'{{job="{self._prom_label(ns)}",'
                          f'rank="{rank}"}}')
                for name in sorted(counters):
                    sample(f"zmpi_spc_{self._prom_name(name)}",
                           "gauge" if name in watermarks else "counter",
                           labels, counters[name])
                if "staleness_s" in rec:
                    sample("zmpi_metrics_age_seconds", "gauge", labels,
                           f"{rec['staleness_s']:.3f}")
        lines: list[str] = []
        for metric in sorted(families):
            kind, rows = families[metric]
            lines.append(f"# TYPE {metric} {kind}")
            lines.extend(rows)
        return "\n".join(lines) + ("\n" if lines else "")

    def _stream(self, job: _Job, payload: list) -> None:
        """One frame to the job's IOF client; a departed client must
        never wedge the daemon (output is dropped, children keep
        draining so their pipes never block)."""
        from ..pt2pt.tcp import _send_frame
        from ..utils import dss

        if job.io_broken:
            return
        try:
            with job.conn_lock:
                _send_frame(job.conn, dss.pack(payload))
        except OSError:
            job.io_broken = True

    # -- launch ----------------------------------------------------------

    def _rank_env(self, job: _Job, rank: int,
                  rejoin: "tuple[int, list[int]] | None" = None) -> dict:
        """The ZMPI_* contract of a daemon-hosted rank: PMIx-served
        modex (no coordinator address at all), the daemon's own address
        for the relaunch RPC, and the per-job session tag the /dev/shm
        sweep keys on.  Stale ZMPI_* from the daemon's OWN launch
        environment is scrubbed — a daemon started under zmpirun must
        not leak its launcher's contract into its children."""
        env = {k: v for k, v in os.environ.items()
               if not k.startswith("ZMPI_")}
        env.update({
            "ZMPI_RANK": str(rank),
            "ZMPI_SIZE": str(job.size),
            "ZMPI_PMIX": f"{self.host}:{self.pmix.address[1]}/{job.id}",
            "ZMPI_DVM": f"{self.host}:{self.address[1]}",
            "ZMPI_JOB": job.id,
            "ZMPI_SESSION": job.session,
        })
        if job.ft:
            env["ZMPI_FT"] = "1"
        if job.metrics:
            # the opt-in metrics plane: every rank of this job runs the
            # spc publisher against the resident store
            env["ZMPI_METRICS"] = "1"
        if job.trace:
            # the tracing plane rides the metrics publisher: every
            # rank arms its span recorder and ships trace:<job>:<rank>
            env["ZMPI_TRACE"] = "1"
        if rejoin is not None:
            # recovery-window metadata: the bumped namespace generation
            # and the whole batch of co-respawned ranks, so each
            # replacement reads its siblings' cards at the FRESH
            # generation (the corpse's old card must not satisfy it)
            gen, batch = rejoin
            env["ZMPI_REJOIN"] = "1"
            env["ZMPI_REJOIN_GEN"] = str(gen)
            env["ZMPI_REJOIN_RANKS"] = ",".join(str(r) for r in batch)
        pkg_root = _pkg_root()
        parts = env.get("PYTHONPATH", "").split(os.pathsep)
        if pkg_root not in parts:
            env["PYTHONPATH"] = os.pathsep.join(
                [pkg_root] + [p for p in parts if p])
        for name, value in job.mca or ():
            env[f"ZMPI_MCA_{name}"] = str(value)
        return env

    def _spawn_rank(self, job: _Job, rank: int,
                    rejoin: "tuple[int, list[int]] | None" = None
                    ) -> subprocess.Popen:
        p = subprocess.Popen(
            job.cmds[rank],
            env=self._rank_env(job, rank, rejoin=rejoin),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            start_new_session=True,  # isolate from the daemon's signals
        )
        for stream, label in ((p.stdout, ""), (p.stderr, ":err")):
            t = threading.Thread(
                target=self._drain_iof, args=(job, rank, label, stream),
                daemon=True, name=f"dvm-iof-{job.id}-{rank}{label}",
            )
            t.start()
            job.drains.append(t)
        w = threading.Thread(
            target=self._watch_child, args=(job, rank, p),
            daemon=True, name=f"dvm-wait-{job.id}-{rank}",
        )
        w.start()
        return p

    def _drain_iof(self, job: _Job, rank: int, label: str, stream) -> None:
        for line in iter(stream.readline, ""):
            self._stream(job, ["io", rank, label, line])
        stream.close()

    def _handle_launch(self, spec: dict, conn, conn_lock) -> None:
        n = int(spec["n"])
        if n < 1:
            raise errors.ArgError("zprted launch: n must be >= 1")
        argv = [str(a) for a in spec["argv"]]
        cmd = [sys.executable] + argv if argv[0].endswith(".py") else argv
        timeout = spec.get("timeout")
        with self._lock:
            job_id = f"job{next(self._job_ids)}"
            job = _Job(
                job_id, n, [list(cmd)] * n, bool(spec.get("ft")),
                [tuple(m) for m in (spec.get("mca") or [])],
                f"{self.session}_{job_id}",
                conn, conn_lock,
                metrics=bool(spec.get("metrics")),
                # trace implies metrics (the publisher ships the span
                # buffers): a trace-only launch gets both planes
                trace=bool(spec.get("trace")),
            )
            if job.trace:
                job.metrics = True
            self._jobs[job_id] = job
        # the namespace IS the jobid: ranks modex through the resident
        # store with zero per-job rendezvous infrastructure
        self.store.ensure_ns(job_id, n)
        self._stream(job, ["job", job_id])
        with job.lock:
            for rank in range(n):
                job.procs[rank] = self._spawn_rank(job, rank)
                job.live += 1
        spc.record("dvm_jobs_launched")
        # a job with no deadline of its own still may not park this
        # handler forever on a wedged rank set
        timeout = timeout if timeout \
            else float(mca_var.get("dvm_job_timeout", 600.0))
        if not job.done.wait(timeout):
            self._stream(job, ["note",
                               f"zprted: job {job_id} timeout after "
                               f"{timeout}s; killing it\n"])
            self._teardown_job(job, rc=124)
        # IOF flushes before the exit frame: each drain exits at its
        # stream's EOF, which the children's deaths guarantee
        for t in list(job.drains):
            t.join(timeout=2.0)
        with job.lock:
            if job.stopping:
                # abort/timeout teardown: the first failure (or 124) is
                # the job's code — the zmpirun contract
                rc = int(job.fail_rc or 0)
            else:
                # ran to completion: judge each rank by its LATEST
                # incarnation — a respawned-over corpse's exit status is
                # recovery history, not a job failure
                bad = [c for c in job.rcs.values() if c != 0]
                rc = (128 - bad[0] if bad[0] < 0 else int(bad[0])) \
                    if bad else 0
        self._stream(job, ["exit", rc])
        self._finalize_job(job)

    # -- child watching / fault events -----------------------------------

    def _watch_child(self, job: _Job, rank: int,
                     p: subprocess.Popen) -> None:
        """One BLOCKING waitpid per child — the daemon's failure source
        is the OS, not a timeout."""
        rc = p.wait()
        with job.lock:
            # exit accounting happens EXACTLY once per proc: here, or in
            # the respawn RPC's corpse-adoption path if it won the race
            if getattr(p, "_dvm_accounted", False):
                return
            p._dvm_accounted = True
            current = job.procs.get(rank) is p
            if current:
                job.rcs[rank] = rc
            job.live -= 1
            last = job.live == 0
            stopping = job.stopping
            if current and rc != 0 and not stopping \
                    and job.fail_rc is None:
                # signal death → 128+sig (the shell convention)
                job.fail_rc = 128 - rc if rc < 0 else rc
        if current and rc != 0 and not stopping:
            norm = 128 - rc if rc < 0 else rc
            if job.ft:
                # authoritative fault event: the survivors learn NOW,
                # from OS truth, not after a heartbeat window
                self._flood_fault(job, rank, rc)
            else:
                # MPI_Abort semantics (the zmpirun contract): one rank
                # failed, the job is over
                self._stream(job, ["note",
                                   f"zprted: rank {rank} exited with "
                                   f"code {norm}; terminating job "
                                   f"{job.id}\n"])
                self._teardown_job(job, rc=norm)
                return
        if last and not stopping:
            job.done.set()

    def _flood_fault(self, job: _Job, rank: int, rc: int) -> None:
        """FT_DVM_CID to every survivor of the job, addressed from the
        name-served cards — the daemon holds the book, so the flood
        reaches even ranks the corpse never exchanged data with."""
        from ..pt2pt.tcp import _send_frame
        from ..ft import ulfm
        from ..utils import dss

        spc.record("dvm_fault_events")
        mca_output.verbose(
            2, _stream, "job %s: rank %d died (rc=%d); flooding fault "
            "event", job.id, rank, rc,
        )
        hello = dss.pack(["d", -1])
        frame = dss.pack(-1, 0, ulfm.FT_DVM_CID, 0, [[rank, int(rc)]])

        def notify(addr):
            try:
                sock = socket.create_connection(addr, 2.0)
            except OSError:
                return  # also dying: its own watcher's course
            try:
                _send_frame(sock, hello)
                _send_frame(sock, frame)
            except OSError:
                pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

        # one short-lived thread per survivor: the whole point of this
        # event is beating the heartbeat window, so a co-dying rank's
        # connect timeout (or a not-yet-modexed card) must not serialize
        # ahead of the survivors still waiting to hear
        for r in job.alive_ranks():
            if r == rank:
                continue
            try:
                card = self.store.get(job.id, f"card:{r}", timeout=0.05)
            except errors.MpiError:
                continue  # not modexed yet: nothing to notify
            threading.Thread(
                target=notify, args=((card[0], int(card[1])),),
                daemon=True, name=f"dvm-fault-{job.id}-{r}",
            ).start()

    def _handle_respawn(self, job_id: str, ranks: list[int]) -> list[int]:
        """The relaunch RPC: exec a fresh OS process per victim.  ONE
        generation bump covers the whole batch — N replacements of one
        recovery window publish their fresh cards under the same tag
        and FT_JOIN the same name-served job."""
        job = self._job(job_id)
        if job.done.is_set():
            raise errors.ArgError(
                f"zprted: job {job_id} already completed")
        if not ranks:
            return []
        pids = []
        with job.lock:
            # validate the WHOLE batch before spawning any of it: a bad
            # rank must not leave a half-respawned recovery window
            for rank in ranks:
                if not 0 <= rank < job.size:
                    raise errors.ArgError(
                        f"zprted respawn: rank {rank} outside job "
                        f"{job_id} (size {job.size})")
            for rank in ranks:
                old = job.procs.get(rank)
                if old is not None and old.poll() is None:
                    # a victim the survivors AGREED dead whose OS
                    # process still exists is wedged (deadlock,
                    # SIGSTOP, half-dead) — the PRRTE contract kills
                    # the declared-dead incarnation before respawning,
                    # it never refuses the recovery
                    try:
                        os.killpg(os.getpgid(old.pid), signal.SIGKILL)
                    except (OSError, ProcessLookupError):
                        pass
                    try:
                        # zlint: disable=ZL002 -- the respawn batch is atomic under job.lock by design (generation window + exit accounting); the reap of a SIGKILLed corpse is bounded to 5 s
                        old.wait(timeout=5.0)
                    except subprocess.TimeoutExpired:
                        raise errors.InternalError(
                            f"zprted respawn: wedged rank {rank} of "
                            f"{job_id} survived SIGKILL")
            gen = self.store.bump_generation(job_id)
            batch = sorted(ranks)
            for rank in ranks:
                old = job.procs.get(rank)
                if old is not None:
                    if not getattr(old, "_dvm_accounted", False):
                        # adopt the corpse's exit before its watcher
                        # does: the once-per-proc accounting contract
                        old._dvm_accounted = True
                        job.rcs[rank] = old.returncode
                        job.live -= 1
                    job.superseded.setdefault(rank, []).append(old)
                p = self._spawn_rank(job, rank, rejoin=(gen, batch))
                job.procs[rank] = p
                job.rcs.pop(rank, None)
                job.live += 1
                pids.append(p.pid)
        spc.record("dvm_respawns", len(ranks))
        return pids

    # -- teardown ---------------------------------------------------------

    def _teardown_job(self, job: _Job, rc: int) -> None:
        with job.lock:
            job.stopping = True
            if job.fail_rc is None or rc == 124:
                job.fail_rc = rc
            procs = list(job.procs.values())
        for p in procs:
            if p.poll() is None:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGTERM)
                except (OSError, ProcessLookupError):
                    pass
        grace_end = time.monotonic() + _TERM_GRACE
        for p in procs:
            try:
                p.wait(timeout=max(0.0, grace_end - time.monotonic()))
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(os.getpgid(p.pid), signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    pass
                p.wait()
        job.done.set()

    def _finalize_job(self, job: _Job) -> None:
        """End-of-job hygiene: reap superseded corpses, drop the
        namespace, sweep the job's /dev/shm artifacts (killed ranks
        never unlink their own rings)."""
        with job.lock:
            leftovers = [p for ps in job.superseded.values() for p in ps]
        for p in leftovers:
            try:
                p.wait(timeout=1.0)
            except subprocess.TimeoutExpired:
                pass
        self.store.destroy_ns(job.id)
        _sweep_shm(job.session)
        with self._lock:
            self._jobs.pop(job.id, None)

    def stop(self) -> None:
        """Orderly daemon shutdown: kill every live job, drop the store,
        close both listeners (the shared shutdown ladder), sweep the
        session."""
        if self.closed:
            return
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            self._teardown_job(job, rc=143)
            self._finalize_job(job)
        if self.metrics_http is not None:
            self.metrics_http.close()
        self.pmix.close()
        super().close()
        _sweep_shm(self.session)
        self._stop_evt.set()

    def close(self) -> None:
        """The RPC-scaffold name for :meth:`stop` — a Dvm closed like a
        bare server still tears its jobs down."""
        self.stop()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the daemon is stopped (RPC or signal)."""
        return self._stop_evt.wait(timeout)


class DvmClient:
    """Client handle to a running daemon — ``zmpirun --dvm`` and the
    recovery pipeline's relaunch RPC both speak through this."""

    def __init__(self, address: tuple[str, int] | str,
                 timeout: float = 30.0):
        self.address = pmix_mod.parse_addr(address)
        self._timeout = timeout
        self.last_job_id: str | None = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        try:
            self._sock.connect(self.address)
        except OSError as e:
            self._sock.close()
            raise errors.InternalError(
                f"zprted: no daemon at {self.address}: {e}"
            ) from e

    def _call(self, req: list, wait: float | None = None) -> Any:
        from ..pt2pt.tcp import _recv_frame, _send_frame
        from ..utils import dss

        self._sock.settimeout((wait or 0.0) + self._timeout)
        try:
            _send_frame(self._sock, dss.pack(req))
            frame = _recv_frame(self._sock)
        except OSError as e:
            raise errors.InternalError(
                f"zprted: daemon connection lost mid-{req[0]}: {e}"
            ) from e
        if frame is None:
            raise errors.InternalError(
                f"zprted: daemon closed the connection mid-{req[0]}")
        [status, value] = dss.unpack(frame)[0]
        if status != "ok":
            raise errors.InternalError(f"zprted {req[0]}: {value}")
        return value

    def launch(self, n: int, argv: list[str],
               mca: list | None = None, ft: bool = False,
               timeout: float | None = None, tag_output: bool = True,
               stdout=None, stderr=None, metrics: bool = False,
               trace: bool = False) -> int:
        """Launch an n-rank job into the resident VM; streams its IOF
        and returns the job exit code (the ``zmpirun`` surface, minus
        the per-job launcher)."""
        from ..pt2pt.tcp import _recv_frame, _send_frame
        from ..utils import dss

        stdout = stdout if stdout is not None else sys.stdout
        stderr = stderr if stderr is not None else sys.stderr
        spec = {"n": int(n), "argv": [str(a) for a in argv],
                "mca": [list(m) for m in (mca or [])], "ft": bool(ft),
                "timeout": timeout, "metrics": bool(metrics),
                "trace": bool(trace)}
        # no client-imposed deadline without an explicit job timeout:
        # the daemon enforces its own (tunable) dvm_job_timeout and
        # ALWAYS sends the exit frame, and a daemon crash surfaces as
        # EOF/reset — a hard-coded recv timeout here would desync from
        # a raised server-side limit and abandon a healthy job's IOF
        self._sock.settimeout(timeout + 30.0 if timeout else None)
        try:
            _send_frame(self._sock, dss.pack(["launch", spec]))
            while True:
                frame = _recv_frame(self._sock)
                if frame is None:
                    raise errors.InternalError(
                        "zprted: daemon vanished mid-job")
                [msg] = dss.unpack(frame)
                kind = msg[0]
                if kind == "job":
                    self.last_job_id = msg[1]
                elif kind == "io":
                    _, rank, label, line = msg
                    sink = stderr if label else stdout
                    if tag_output:
                        sink.write(f"[{rank}{label}] {line}")
                    else:
                        sink.write(line)
                    sink.flush()
                elif kind == "note":
                    stderr.write(msg[1])
                    stderr.flush()
                elif kind == "exit":
                    return int(msg[1])
                elif kind == "err":
                    raise errors.InternalError(f"zprted launch: {msg[1]}")
        except OSError as e:
            raise errors.InternalError(
                f"zprted: daemon connection lost mid-job: {e}") from e

    def respawn(self, job_id: str, ranks: list[int],
                timeout: float = 30.0) -> list[int]:
        return self._call(["respawn", str(job_id),
                           [int(r) for r in ranks]], wait=timeout)

    def pids(self, job_id: str) -> dict[int, int]:
        return {int(r): int(p)
                for r, p in self._call(["pids", str(job_id)]).items()}

    def stat(self) -> dict:
        return self._call(["stat"])

    def metrics(self, job_id: str, rank: int | None = None,
                timeout: float = 10.0) -> dict:
        """Fleet-visible metrics: one rank's published snapshot, or the
        whole job's per-rank + aggregated view (staleness-stamped)."""
        req: list = ["metrics", str(job_id)]
        if rank is not None:
            req.append(int(rank))
        return self._call(req, wait=timeout)

    def ping(self) -> bool:
        return self._call(["ping"]) == "pong"

    def stop(self) -> bool:
        return bool(self._call(["stop"]))

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def main(args: list[str] | None = None) -> int:
    """The ``zprted`` CLI: start a daemon, announce its ports, run until
    signalled or stopped by RPC."""
    ap = argparse.ArgumentParser(
        prog="zprted",
        description="Persistent runtime daemon (PRRTE/DVM analog): "
                    "hosts the PMIx store, launches zmpirun --dvm jobs, "
                    "watches children, floods fault events, respawns "
                    "ranks.",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="control (RPC) port; 0 = ephemeral")
    ap.add_argument("--pmix-port", type=int, default=0,
                    help="PMIx store port; 0 = ephemeral")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="bind the HTTP GET /metrics scrape endpoint "
                         "(Prometheus text exposition) on this port; "
                         "0 = ephemeral; off by default")
    ns = ap.parse_args(args)
    dvm = Dvm(ns.host, ns.port, ns.pmix_port,
              metrics_port=ns.metrics_port)
    extra = ""
    if dvm.metrics_http is not None:
        extra = (f" metrics={dvm.host}:"
                 f"{dvm.metrics_http.address[1]}")
    print(f"zprted ready dvm={dvm.host}:{dvm.address[1]} "
          f"pmix={dvm.host}:{dvm.pmix.address[1]}{extra}", flush=True)

    def on_signal(signum, _frame):
        dvm.stop()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    dvm.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
