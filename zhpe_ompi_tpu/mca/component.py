"""MCA component architecture: frameworks, components, priority selection.

Re-design of the reference's Modular Component Architecture
(``opal/mca/mca.h:1-403``, ``opal/mca/base/mca_base_framework.c``,
``mca_base_components_select.c``): a *framework* is a fixed interface (e.g.
"coll"); a *component* is one implementation (e.g. "tpu", "tuned", "basic");
a *module* is a per-communicator instance returned by the component's query.

Selection semantics match the reference:

- The framework-name MCA variable holds an include list
  (``ZMPI_MCA_coll=tpu,tuned``) or an exclude list (``ZMPI_MCA_coll=^basic``)
  — mixing both is an error, as in ``mca_base_component_find.c``.
- Each component registers ``<fw>_<name>_priority``; among the admitted
  components, higher priority wins.
- Component availability is dynamic: a component's ``available()`` may refuse
  (e.g. the tpu component on a host with no accelerator), mirroring
  ``component_init`` probing hardware.

Python components are the in-tree analog of static components; third-party
packages can register components via :func:`Framework.register` at import
time, the analog of DSO component discovery
(``mca_base_component_repository.c:361-432``).
"""

from __future__ import annotations

import threading
from typing import Any

from ..core import errors
from . import output as mca_output
from . import var as mca_var


class Component:
    """Base class for all MCA components."""

    #: Framework this component belongs to (e.g. "coll").
    framework_name: str = ""
    #: Component name (e.g. "tuned").
    name: str = ""
    #: Default selection priority; overridable via <fw>_<name>_priority.
    default_priority: int = 0
    #: Version triple for introspection (ompi_info analog).
    version: tuple[int, int, int] = (1, 0, 0)

    def __init__(self) -> None:
        self._priority_var = mca_var.register(
            f"{self.framework_name}_{self.name}_priority",
            self.default_priority,
            f"Selection priority of the {self.framework_name}/{self.name} component",
            type=int,
        )

    @property
    def priority(self) -> int:
        return int(mca_var.get(self._priority_var.name, self.default_priority))

    def available(self) -> bool:
        """Hardware/environment probe; False removes the component from
        selection (cf. component_init returning NULL)."""
        return True

    def register_params(self) -> None:
        """Register this component's MCA variables (called at framework open)."""

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{self.framework_name}/{self.name} prio={self.priority}>"


def parse_include_exclude(spec: str | None) -> tuple[set[str] | None, set[str]]:
    """Parse a component-list spec into (includes, excludes).

    ``"a,b"`` → include exactly {a,b}; ``"^a,b"`` → exclude {a,b};
    empty/None → no restriction.  Mixing forms raises, as the reference does.
    """
    if not spec:
        return None, set()
    spec = spec.strip()
    if spec.startswith("^"):
        # tolerate a leading ^ on every item ("^a,^b" means exclude both)
        return None, {
            s.strip().lstrip("^") for s in spec[1:].split(",") if s.strip("^ ")
        }
    items = [s.strip() for s in spec.split(",") if s.strip()]
    for it in items:
        if it.startswith("^"):
            raise errors.ArgError(
                f"component list {spec!r} mixes include and exclude forms"
            )
    return set(items), set()


class Framework:
    """A named framework holding registered components."""

    def __init__(self, name: str, description: str = "") -> None:
        self.name = name
        self.description = description
        self._components: dict[str, Component] = {}
        self._lock = threading.RLock()
        self._opened = False
        self._stream = mca_output.open_stream(name)
        self._select_var = mca_var.register(
            name,
            "",
            f"Comma-separated list of {name} components to include "
            f"(or ^list to exclude)",
            type=str,
        )

    def register(self, component: Component) -> Component:
        with self._lock:
            if component.name in self._components:
                return self._components[component.name]
            self._components[component.name] = component
            mca_output.verbose(
                10, self._stream, "registered component %s", component.name
            )
            return component

    def open(self) -> None:
        with self._lock:
            if self._opened:
                return
            for comp in self._components.values():
                comp.register_params()
            self._opened = True

    def close(self) -> None:
        with self._lock:
            self._opened = False

    def components(self) -> list[Component]:
        with self._lock:
            return list(self._components.values())

    def admitted(self) -> list[Component]:
        """Components admitted by the include/exclude list and available(),
        sorted by descending priority (stable for equal priorities)."""
        spec = mca_var.get(self.name, "")
        includes, excludes = parse_include_exclude(spec)
        out = []
        with self._lock:
            for comp in self._components.values():
                if includes is not None and comp.name not in includes:
                    continue
                if comp.name in excludes:
                    continue
                if not comp.available():
                    mca_output.verbose(
                        5, self._stream, "component %s not available", comp.name
                    )
                    continue
                out.append(comp)
        out.sort(key=lambda c: -c.priority)
        return out

    def select_one(self) -> Component:
        """Select exactly one component (the pml-style exclusive selection,
        ``mca_pml_base_select``)."""
        adm = self.admitted()
        if not adm:
            raise errors.InternalError(
                f"no available component in framework {self.name!r}"
            )
        winner = adm[0]
        mca_output.verbose(1, self._stream, "selected component %s", winner.name)
        return winner


class FrameworkRegistry:
    def __init__(self) -> None:
        self._frameworks: dict[str, Framework] = {}
        self._lock = threading.Lock()

    def framework(self, name: str, description: str = "") -> Framework:
        with self._lock:
            fw = self._frameworks.get(name)
            if fw is None:
                fw = Framework(name, description)
                self._frameworks[name] = fw
            return fw

    def all_frameworks(self) -> list[Framework]:
        with self._lock:
            return sorted(self._frameworks.values(), key=lambda f: f.name)


registry = FrameworkRegistry()
framework = registry.framework


def build_framework(name: str, description: str,
                    component_factories) -> Framework:
    """Memoized framework construction: first call registers the
    components (built from the zero-arg factories) and opens; later
    calls return the populated framework without reconstructing anything.
    The single home for the build-once pattern every
    ``<fw>_framework()`` helper needs."""
    fw = registry.framework(name, description)
    if not fw.components():
        for factory in component_factories:
            fw.register(factory())
        fw.open()
    return fw


def info() -> list[dict[str, Any]]:
    """Introspection dump used by the zmpi-info tool (ompi_info analog)."""
    out = []
    for fw in registry.all_frameworks():
        out.append(
            {
                "framework": fw.name,
                "description": fw.description,
                "components": [
                    {
                        "name": c.name,
                        "priority": c.priority,
                        "version": ".".join(map(str, c.version)),
                        "available": c.available(),
                    }
                    for c in fw.components()
                ],
            }
        )
    return out
