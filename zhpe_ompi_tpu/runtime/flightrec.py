"""Flight recorder — a fixed-size ring of typed runtime events.

The PERUSE-adjacent half of the observability plane: where SPC counters
say *how much* happened, the flight recorder says *what, in order* — a
lock-cheap per-process ring of small typed events recorded at the
existing seams (send/recv post, matching, collective phase enter/exit,
FT classification, revoke, respawn).  When a typed failure
classification lands, the metrics publisher (``runtime/spc.py``)
publishes the survivor's last-N window to the PMIx store under
``flightrec:<job>:<rank>`` — a postmortem of a real-process kill shows
what every survivor was doing at classification time, with the
classification event itself as the tail entry.

Cost discipline mirrors :mod:`.peruse`: the whole recorder is ARMED
only while a metrics publisher (or a test) holds the refcount —
``arm()``/``disarm()`` flip the module gate AND the PERUSE match-event
subscription together, so a process with no publisher pays exactly one
false module-attribute check per seam and the matching hot path pays
nothing at all.  While armed, a seam pays one slot write under a plain
lock (no allocation beyond the event dict, no I/O, no waiting).

The ring OVERWRITES: an event that displaces an unread slot counts in
the ``flightrec_events_dropped`` SPC counter (events lost to the
postmortem window — a window smaller than the traffic between
snapshots is visible, not silent).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from ..mca import var as mca_var
from . import peruse, spc

mca_var.register(
    "flightrec_capacity", 256,
    "Slots in the per-process flight-recorder ring (the last-N window "
    "published to the store on a typed failure classification); the "
    "ring overwrites, counting displaced events in "
    "flightrec_events_dropped",
    type=int,
)

# event types (the seams that record them)
SEND = "send"              # pt2pt/tcp.py send/isend dispatch
RECV = "recv"              # pt2pt/tcp.py recv post
MATCH = "match"            # matching engines, via the PERUSE events
COLL_ENTER = "coll_enter"  # coll/han.py schedule + phase entry
COLL_EXIT = "coll_exit"    # coll/han.py schedule + phase completion
FT_CLASS = "ft_class"      # ft/ulfm.py FailureState classification
REVOKE = "revoke"          # ft/ulfm.py cid revocation
RESPAWN = "respawn"        # ft/recovery.py respawn pipelines
RESIZE = "resize"          # runtime/dvm.py resize RPC + elastic-session
                           # membership changes (ft/recovery.py)
DAEMON_FAULT = "daemon_fault"  # runtime/dvm.py fault routing (a rank's
                           # waitpid death or a lost daemon subtree)
DEVICE_FAULT = "device_fault"  # parallel/mesh.py device liveness probe:
                           # a missed deadline classified cause="device"
                           # (probe kind + victim rank ride the event)
CKPT_BEGIN = "ckpt_begin"  # io/ckptio.py collective checkpoint write
                           # accepted (snapshot captured, stream begins)
CKPT_COMMIT = "ckpt_commit"  # io/ckptio.py manifest published atomically
                           # (steps between begin/commit = async overlap)
CKPT_RESTORE = "ckpt_restore"  # ft/recovery.py rollback leg: restore
                           # from the newest COMPLETE step (bytes +
                           # step + integrity rejects ride the event)

ALL_EVENTS = (SEND, RECV, MATCH, COLL_ENTER, COLL_EXIT, FT_CLASS,
              REVOKE, RESPAWN, RESIZE, DAEMON_FAULT, DEVICE_FAULT,
              CKPT_BEGIN, CKPT_COMMIT, CKPT_RESTORE)

#: hot-path gate (the peruse cost discipline): seams check this bare
#: module attribute before paying the record() call.  False until a
#: metrics publisher arms the recorder — a ring nobody will ever
#: publish is not worth one event dict per message
active = False


class FlightRecorder:
    """The ring itself: ``capacity`` fixed slots, a monotonically
    increasing sequence, overwrite-with-accounting.  The module-level
    recorder is per-process (thread ranks share it, exactly like the
    SPC registry); tests construct private instances."""

    def __init__(self, capacity: int | None = None):
        cap = int(mca_var.get("flightrec_capacity", 256)) \
            if capacity is None else int(capacity)
        self._cap = max(8, cap)
        self._slots: list[dict | None] = [None] * self._cap
        self._n = 0  # total events ever recorded (next seq)
        self._lock = threading.Lock()
        # merge-safe clock domain (shared with runtime/ztrace.py):
        # events stamp monotonic ns — a wall clock stepping under NTP
        # mid-window would corrupt cross-rank ordering — and the ring
        # carries ONE wall anchor captured back-to-back with its
        # monotonic twin, so consumers map stamps onto the wall clock
        # through a fixed offset
        self.anchor_wall = time.time()
        self.anchor_mono_ns = time.monotonic_ns()

    @property
    def capacity(self) -> int:
        return self._cap

    def anchors(self) -> tuple[float, int]:
        """(anchor_wall, anchor_mono_ns): the ring's clock anchor —
        ``anchor_wall + (t_ns - anchor_mono_ns)/1e9`` is an event's
        wall time."""
        return self.anchor_wall, self.anchor_mono_ns

    def record(self, etype: str, **fields: Any) -> None:
        """One typed event: seq + monotonic-ns stamp + the caller's
        small DSS-packable fields.  Lock-cheap: slot write and index
        bump."""
        evt = {"t_ns": time.monotonic_ns(), "type": etype}
        evt.update(fields)
        with self._lock:
            i = self._n % self._cap
            dropped = self._slots[i] is not None
            evt["seq"] = self._n
            self._slots[i] = evt
            self._n += 1
        if dropped:
            spc.record("flightrec_events_dropped")

    def window(self, n: int | None = None) -> list[dict]:
        """The last ``n`` (default: whole ring) events in record order —
        the postmortem view the publisher ships to the store."""
        with self._lock:
            total = self._n
            have = min(total, self._cap)
            want = have if n is None else min(int(n), have)
            out = []
            for seq in range(total - want, total):
                evt = self._slots[seq % self._cap]
                if evt is not None:
                    out.append(dict(evt))
        return out

    def total(self) -> int:
        """Events ever recorded (seq of the next event)."""
        with self._lock:
            return self._n

    def clear(self) -> None:
        with self._lock:
            self._slots = [None] * self._cap
            self._n = 0
            # a fresh window gets a fresh anchor: the old pair mapped
            # stamps nobody can see anymore
            self.anchor_wall = time.time()
            self.anchor_mono_ns = time.monotonic_ns()


_recorder = FlightRecorder()


def record(etype: str, **fields: Any) -> None:
    """Record into the process-global ring (no-op while ``active`` is
    False — the seams' one-boolean gate)."""
    if active:
        _recorder.record(etype, **fields)


def window(n: int | None = None) -> list[dict]:
    return _recorder.window(n)


def anchors() -> tuple[float, int]:
    """(anchor_wall, anchor_mono_ns) of the process-global ring."""
    return _recorder.anchors()


def total() -> int:
    return _recorder.total()


def clear() -> None:
    _recorder.clear()


# -- arming (the module gate + PERUSE match events) -------------------------
#
# Refcounted: each metrics publisher arms on start and disarms on
# stop, so both `active` and `peruse.active` return to False once the
# last publisher is gone (the "inactive costs nothing" contract of
# runtime/peruse.py, applied to the whole recorder).

_arm_lock = threading.Lock()
_arm_count = 0


def _on_match(event: str, **info: Any) -> None:
    record(MATCH, src=int(info.get("src", -1)),
           tag=int(info.get("tag", -1)),
           unexpected=event == peruse.REQ_MATCH_UNEX)


def arm() -> None:
    """Arm the recorder (refcounted): the seams' module gate flips on
    and the PERUSE match events are subscribed."""
    global _arm_count, active
    with _arm_lock:
        _arm_count += 1
        if _arm_count == 1:
            active = True
            peruse.subscribe(peruse.MSG_MATCH_POSTED_REQ, _on_match)
            peruse.subscribe(peruse.REQ_MATCH_UNEX, _on_match)


def disarm() -> None:
    global _arm_count, active
    with _arm_lock:
        if _arm_count == 0:
            return
        _arm_count -= 1
        if _arm_count == 0:
            active = False
            peruse.unsubscribe(peruse.MSG_MATCH_POSTED_REQ, _on_match)
            peruse.unsubscribe(peruse.REQ_MATCH_UNEX, _on_match)
