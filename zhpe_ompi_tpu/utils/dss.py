"""DSS — typed data serialization for the out-of-band plane.

Re-design of ``opal/dss`` (SURVEY.md §2.1, 6.2k LoC): the reference packs
typed values (ints of every width, strings, byte objects, nested
containers) into self-describing buffers for PMIx modex payloads and tool
messages.  Same role here: the host plane's wire format for the multi-host
DCN transport and for checkpoint metadata — numpy arrays carry their dtype
and shape, containers nest, and every value round-trips exactly.

Format: one type byte, then a varint length where needed, then the
payload; containers recurse.  Little-endian fixed-width scalars (the
reference's heterogeneous-arch conversion lives in the datatype engine's
external32 path, not here).

Zero-copy frame path (the btl-style "send the buffer, not a copy of it"
contract): :func:`pack_frames` splits a frame into a self-describing
header stream plus out-of-band raw buffer segments — contiguous
ndarray/bytes payloads are referenced as memoryviews, never
``tobytes()``-copied.  On the wire the frame is simply the header
followed by the segments in order, so ``header + b"".join(segments)`` is
a valid :func:`unpack` stream: the OOB tags carry the payload's offset
from the END of the frame, patched into the header once every segment's
size is known.  A legacy :func:`pack` stream contains no OOB tags and is
therefore the degenerate case of the same format — mixed old/new frames
round-trip through one parser.  :func:`unpack_from` additionally builds
arrays as views OVER a writable receive buffer (``recv_into`` target)
instead of copying them out.
"""

from __future__ import annotations

import struct
from typing import Any

import numpy as np

from ..core import errors

_T_NONE = 0
_T_BOOL = 1
_T_INT = 2       # arbitrary-precision python int (zigzag varint)
_T_FLOAT = 3     # python float, f64
_T_STR = 4
_T_BYTES = 5
_T_LIST = 6
_T_TUPLE = 7
_T_DICT = 8
_T_NDARRAY = 9
# out-of-band twins: the header carries dtype/shape/nbytes plus an 8-byte
# offset-from-frame-end; the raw payload travels as a trailing segment
_T_NDARRAY_OOB = 10
_T_BYTES_OOB = 11

_OFE = struct.Struct("<Q")  # offset-from-end slot, patched post-pack

# bytes/bytearray below this stay inline even on the frame path: their
# unpack must copy anyway (``bytes`` is immutable), so OOB only saves the
# pack-side copy — worth it for bulk blobs, not for tag strings
_BYTES_OOB_MIN = 4096


def _pack_varint(n: int, out: bytearray) -> None:
    if n < 0:
        raise errors.ArgError("varint must be non-negative")
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _unpack_varint(buf: memoryview, pos: int) -> tuple[int, int]:
    n = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _pack_one(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(_T_NONE)
    elif isinstance(obj, bool):
        out.append(_T_BOOL)
        out.append(1 if obj else 0)
    elif isinstance(obj, int):
        out.append(_T_INT)
        # zigzag so negatives stay compact
        z = (obj << 1) if obj >= 0 else ((-obj << 1) | 1)
        _pack_varint(z, out)
    elif isinstance(obj, float):
        out.append(_T_FLOAT)
        out.extend(struct.pack("<d", obj))
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(_T_STR)
        _pack_varint(len(raw), out)
        out.extend(raw)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(_T_BYTES)
        _pack_varint(len(obj), out)
        out.extend(obj)
    elif isinstance(obj, np.ndarray):
        out.append(_T_NDARRAY)
        dt = obj.dtype.str.encode("ascii")  # e.g. b'<f4'
        _pack_varint(len(dt), out)
        out.extend(dt)
        _pack_varint(obj.ndim, out)
        for d in obj.shape:
            _pack_varint(d, out)
        raw = np.ascontiguousarray(obj).tobytes()
        _pack_varint(len(raw), out)
        out.extend(raw)
    elif isinstance(obj, (list, tuple)):
        out.append(_T_LIST if isinstance(obj, list) else _T_TUPLE)
        _pack_varint(len(obj), out)
        for item in obj:
            _pack_one(item, out)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        _pack_varint(len(obj), out)
        for k, v in obj.items():
            _pack_one(k, out)
            _pack_one(v, out)
    elif isinstance(obj, np.generic):
        # numpy scalar: pack as a 0-d array so the dtype survives
        _pack_one(np.asarray(obj), out)
    else:
        raise errors.TypeError_(
            f"dss cannot pack {type(obj).__name__}"
        )


class _UnpackCtx:
    """Per-stream unpack state: ``copy`` forces fresh writable arrays
    (legacy semantics); ``oob`` accumulates trailing out-of-band bytes
    consumed, so the final truncation check still balances."""

    __slots__ = ("copy", "oob")

    def __init__(self, copy: bool):
        self.copy = copy
        self.oob = 0


def _ndarray_from(buf: memoryview, dt: np.dtype, shape: list[int],
                  ctx: _UnpackCtx) -> np.ndarray:
    """Array over a region of the frame buffer: a VIEW when the caller
    allows it (writable recv buffer), else one fresh writable copy."""
    arr = np.frombuffer(buf, dtype=dt).reshape(shape)
    if ctx.copy or not arr.flags.writeable:
        arr = arr.copy()
    return arr


def _unpack_one(buf: memoryview, pos: int,
                ctx: _UnpackCtx) -> tuple[Any, int]:
    t = buf[pos]
    pos += 1
    if t == _T_NONE:
        return None, pos
    if t == _T_BOOL:
        return bool(buf[pos]), pos + 1
    if t == _T_INT:
        z, pos = _unpack_varint(buf, pos)
        return ((z >> 1) if not z & 1 else -(z >> 1)), pos
    if t == _T_FLOAT:
        (v,) = struct.unpack_from("<d", buf, pos)
        return v, pos + 8
    if t == _T_STR:
        n, pos = _unpack_varint(buf, pos)
        return bytes(buf[pos : pos + n]).decode("utf-8"), pos + n
    if t == _T_BYTES:
        n, pos = _unpack_varint(buf, pos)
        return bytes(buf[pos : pos + n]), pos + n
    if t == _T_NDARRAY:
        n, pos = _unpack_varint(buf, pos)
        dt = np.dtype(bytes(buf[pos : pos + n]).decode("ascii"))
        pos += n
        ndim, pos = _unpack_varint(buf, pos)
        shape = []
        for _ in range(ndim):
            d, pos = _unpack_varint(buf, pos)
            shape.append(d)
        nbytes, pos = _unpack_varint(buf, pos)
        arr = _ndarray_from(buf[pos : pos + nbytes], dt, shape, ctx)
        return arr, pos + nbytes
    if t == _T_NDARRAY_OOB:
        n, pos = _unpack_varint(buf, pos)
        dt = np.dtype(bytes(buf[pos : pos + n]).decode("ascii"))
        pos += n
        ndim, pos = _unpack_varint(buf, pos)
        shape = []
        for _ in range(ndim):
            d, pos = _unpack_varint(buf, pos)
            shape.append(d)
        nbytes, pos = _unpack_varint(buf, pos)
        (ofe,) = _OFE.unpack_from(buf, pos)
        pos += _OFE.size
        start = len(buf) - ofe
        if start < 0 or start + nbytes > len(buf):
            raise errors.TruncateError(
                f"dss: out-of-band segment [{start}:{start + nbytes}] "
                f"outside frame of {len(buf)} bytes"
            )
        ctx.oob += nbytes
        return _ndarray_from(buf[start : start + nbytes], dt, shape,
                             ctx), pos
    if t == _T_BYTES_OOB:
        nbytes, pos = _unpack_varint(buf, pos)
        (ofe,) = _OFE.unpack_from(buf, pos)
        pos += _OFE.size
        start = len(buf) - ofe
        if start < 0 or start + nbytes > len(buf):
            raise errors.TruncateError(
                f"dss: out-of-band segment [{start}:{start + nbytes}] "
                f"outside frame of {len(buf)} bytes"
            )
        ctx.oob += nbytes
        return bytes(buf[start : start + nbytes]), pos
    if t in (_T_LIST, _T_TUPLE):
        n, pos = _unpack_varint(buf, pos)
        items = []
        for _ in range(n):
            item, pos = _unpack_one(buf, pos, ctx)
            items.append(item)
        return (items if t == _T_LIST else tuple(items)), pos
    if t == _T_DICT:
        n, pos = _unpack_varint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _unpack_one(buf, pos, ctx)
            v, pos = _unpack_one(buf, pos, ctx)
            d[k] = v
        return d, pos
    raise errors.TypeError_(f"dss: unknown type tag {t}")


def _oob_view(obj: Any) -> memoryview | None:
    """Flat byte view of a buffer-exporting object, or None when the
    buffer protocol declines (e.g. datetime64 arrays) — callers fall
    back to the inline copy path."""
    try:
        return memoryview(obj).cast("B")
    except (ValueError, TypeError, BufferError):
        return None


def _pack_one_frames(obj: Any, out: bytearray, segs: list[memoryview],
                     slots: list[int], oob_min: int) -> None:
    """Like :func:`_pack_one`, but contiguous ndarray/bytes payloads —
    at any container depth — emit an OOB tag and append a memoryview
    segment instead of copying their raw bytes into the header."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        # scalar precedence must mirror _pack_one exactly: np.float64 IS
        # a float subclass and must stay a _T_FLOAT, not a 0-d array
        _pack_one(obj, out)
        return
    if isinstance(obj, np.ndarray):
        nbytes = int(obj.nbytes)
        if nbytes > 0 and nbytes >= oob_min and obj.flags.c_contiguous:
            view = _oob_view(obj)
            if view is not None:
                out.append(_T_NDARRAY_OOB)
                dt = obj.dtype.str.encode("ascii")
                _pack_varint(len(dt), out)
                out.extend(dt)
                _pack_varint(obj.ndim, out)
                for d in obj.shape:
                    _pack_varint(d, out)
                _pack_varint(nbytes, out)
                slots.append(len(out))
                out.extend(b"\x00" * _OFE.size)
                segs.append(view)
                return
        _pack_one(obj, out)
    elif isinstance(obj, (bytes, bytearray)):
        n = len(obj)
        if n >= max(oob_min, _BYTES_OOB_MIN):
            out.append(_T_BYTES_OOB)
            _pack_varint(n, out)
            slots.append(len(out))
            out.extend(b"\x00" * _OFE.size)
            segs.append(memoryview(obj))
            return
        _pack_one(obj, out)
    elif isinstance(obj, (list, tuple)):
        out.append(_T_LIST if isinstance(obj, list) else _T_TUPLE)
        _pack_varint(len(obj), out)
        for item in obj:
            _pack_one_frames(item, out, segs, slots, oob_min)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        _pack_varint(len(obj), out)
        for k, v in obj.items():
            _pack_one_frames(k, out, segs, slots, oob_min)
            _pack_one_frames(v, out, segs, slots, oob_min)
    elif isinstance(obj, np.generic):
        # numpy scalar: as a 0-d array so the dtype survives (and rides
        # OOB when big enough — np.float64 payloads are the ULFM
        # agreement currency)
        _pack_one_frames(np.asarray(obj), out, segs, slots, oob_min)
    else:
        _pack_one(obj, out)


def pack(*objs: Any) -> bytes:
    """Pack values into one self-describing buffer (opal_dss.pack)."""
    out = bytearray()
    _pack_varint(len(objs), out)
    for obj in objs:
        _pack_one(obj, out)
    return bytes(out)


class _BufferSink:
    """bytearray-shaped adapter over a caller-provided writable buffer:
    the pack machinery appends through it, writing header bytes straight
    into their final destination (a shared-memory ring slot) instead of
    an intermediate bytearray.  Overflow raises ``TruncateError`` — the
    partial write is garbage the caller must discard (an unpublished
    ring slot satisfies this by construction)."""

    __slots__ = ("mv", "pos")

    def __init__(self, buf):
        mv = buf if isinstance(buf, memoryview) else memoryview(buf)
        if mv.readonly:
            raise errors.ArgError("pack_frames_into needs a writable "
                                  "buffer")
        if mv.format != "B" or mv.ndim != 1:
            mv = mv.cast("B")
        self.mv = mv
        self.pos = 0

    def append(self, b: int) -> None:
        if self.pos >= len(self.mv):
            raise errors.TruncateError("dss: pack_frames_into overflow")
        self.mv[self.pos] = b
        self.pos += 1

    def extend(self, data) -> None:
        n = len(data)
        if self.pos + n > len(self.mv):
            raise errors.TruncateError("dss: pack_frames_into overflow")
        self.mv[self.pos : self.pos + n] = bytes(data) \
            if not isinstance(data, (bytes, bytearray, memoryview)) \
            else data
        self.pos += n

    def __len__(self) -> int:
        return self.pos


def pack_frames_into(buf, *objs: Any, oob_min: int = 0
                     ) -> tuple[int, list[memoryview]]:
    """:func:`pack_frames`, but the header stream is packed directly
    into ``buf`` (any writable buffer) — the write-into-buffer variant
    the shared-memory ring's single-slot fast path uses to skip the
    intermediate header bytearray entirely.  Returns
    ``(header_nbytes, segments)``; the on-wire frame is
    ``buf[:header_nbytes]`` followed by the segments in order.  Raises
    ``TruncateError`` when the header alone outgrows ``buf`` (the
    caller discards the partial write and takes the two-step path)."""
    sink = _BufferSink(buf)
    segs: list[memoryview] = []
    slots: list[int] = []
    _pack_varint(len(objs), sink)
    for obj in objs:
        _pack_one_frames(obj, sink, segs, slots, oob_min)
    total = sum(s.nbytes for s in segs)
    prefix = 0
    for slot, seg in zip(slots, segs):
        _OFE.pack_into(sink.mv, slot, total - prefix)
        prefix += seg.nbytes
    return sink.pos, segs


def pack_frames(*objs: Any, oob_min: int = 0
                ) -> tuple[bytes, list[memoryview]]:
    """Pack values into a header stream plus out-of-band raw segments.

    Returns ``(header, segments)`` where the on-wire frame is the
    concatenation ``header + seg0 + seg1 + ...`` — a valid
    :func:`unpack`/:func:`unpack_from` stream.  Contiguous
    ndarray/bytes payloads of at least ``oob_min`` bytes are referenced
    as memoryviews of the CALLER's buffers: nothing is copied here, so
    the caller must keep those buffers unmutated until the segments are
    consumed (a blocking ``sendall``/``sendmsg`` satisfies this by
    construction).  Everything else — and a frame with no qualifying
    payload — degenerates to the legacy inline encoding."""
    out = bytearray()
    segs: list[memoryview] = []
    slots: list[int] = []
    _pack_varint(len(objs), out)
    for obj in objs:
        _pack_one_frames(obj, out, segs, slots, oob_min)
    # patch the offset-from-end slots now every segment size is known:
    # segment i starts (total_tail - prefix_i) bytes before frame end
    total = sum(s.nbytes for s in segs)
    prefix = 0
    for slot, seg in zip(slots, segs):
        _OFE.pack_into(out, slot, total - prefix)
        prefix += seg.nbytes
    return bytes(out), segs


def _unpack(buf: memoryview, copy: bool) -> list[Any]:
    ctx = _UnpackCtx(copy=copy)
    n, pos = _unpack_varint(buf, 0)
    out = []
    for _ in range(n):
        obj, pos = _unpack_one(buf, pos, ctx)
        out.append(obj)
    if pos + ctx.oob != len(buf):
        raise errors.TruncateError(
            f"dss: {len(buf) - pos - ctx.oob} trailing bytes after unpack"
        )
    return out


def unpack(data) -> list[Any]:
    """Unpack every value from a buffer (opal_dss.unpack).  Arrays come
    back as fresh writable copies regardless of the buffer's nature —
    the legacy contract every existing caller holds."""
    return _unpack(memoryview(data), copy=True)


def unpack_from(data) -> list[Any]:
    """Unpack a frame, building arrays as writable VIEWS over ``data``
    when it is a writable buffer (the ``recv_into`` bytearray of the
    zero-copy receive path) — no per-array copy.  The caller must
    dedicate the buffer to this frame: the views keep it alive and
    alias its storage.  Read-only buffers degrade to :func:`unpack`'s
    copy semantics, so delivered arrays are ALWAYS writable."""
    buf = memoryview(data)
    return _unpack(buf, copy=False)
