"""Cartesian topologies (MPI_Cart_*) on the SPMD plane.

Parity targets: ``ompi/mca/topo/base/topo_base_cart_create.c`` (row-major
rank→coords), ``topo_base_cart_shift.c`` (PROC_NULL at non-periodic edges),
``topo_base_cart_sub.c`` (keep/drop dims → sub-communicators),
``ompi/mpi/c/dims_create.c`` (balanced factorization).

TPU shift: ``MPI_Cart_shift`` + ``MPI_Sendrecv`` is ONE collective-permute
with a static uniform pattern; non-periodic boundary ranks receive zeros
(the MPI_PROC_NULL contract: the recv buffer is simply not written — under
SPMD every device must produce a value, so the value is zeros).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..core import errors


def _prime_factors(n: int) -> list[int]:
    out, p = [], 2
    while p * p <= n:
        while n % p == 0:
            out.append(p)
            n //= p
        p += 1
    if n > 1:
        out.append(n)
    return out


def dims_create(nnodes: int, ndims: int,
                dims: Sequence[int] | None = None) -> list[int]:
    """MPI_Dims_create: fill zero entries of `dims` so the product is
    `nnodes`, as balanced as possible (``ompi/mpi/c/dims_create.c``).
    Nonzero entries are constraints and are preserved."""
    if nnodes <= 0:
        raise errors.ArgError(f"nnodes must be positive, got {nnodes}")
    dims = list(dims) if dims is not None else [0] * ndims
    if len(dims) != ndims:
        raise errors.ArgError(f"dims has {len(dims)} entries, ndims={ndims}")
    fixed = 1
    for d in dims:
        if d < 0:
            raise errors.ArgError("negative dimension")
        if d > 0:
            fixed *= d
    if fixed == 0:
        raise errors.ArgError("zero nnodes")
    if nnodes % fixed:
        raise errors.ArgError(
            f"nnodes {nnodes} not divisible by fixed dims (product {fixed})"
        )
    free = [i for i, d in enumerate(dims) if d == 0]
    if not free:
        if fixed != nnodes:
            raise errors.ArgError("fully-constrained dims do not multiply "
                                  f"to nnodes ({fixed} != {nnodes})")
        return dims
    vals = [1] * len(free)
    # multiply each prime factor (largest first) into the smallest slot
    for f in sorted(_prime_factors(nnodes // fixed), reverse=True):
        vals[int(np.argmin(vals))] *= f
    # MPI requires monotonically non-increasing filled dims
    for slot, v in zip(free, sorted(vals, reverse=True)):
        dims[slot] = v
    return dims


class CartTopology:
    """Cartesian topology attached to a communicator.

    Rank numbering is row-major over `dims` exactly as
    ``topo_base_cart_create.c`` computes it; all maps are static numpy
    tables so traced code can consume them as constants.
    """

    def __init__(self, comm, dims: Sequence[int],
                 periods: Sequence[bool] | None = None,
                 reorder: bool = False) -> None:
        self.comm = comm
        self.dims = tuple(int(d) for d in dims)
        self.ndims = len(self.dims)
        if any(d <= 0 for d in self.dims):
            raise errors.ArgError(f"bad dims {self.dims}")
        size = comm.size
        n = int(np.prod(self.dims))
        if n != size:
            raise errors.CommError(
                f"dims {self.dims} (={n}) != comm size {size}"
            )
        self.periods = tuple(
            bool(p) for p in (periods or [False] * self.ndims)
        )
        if len(self.periods) != self.ndims:
            raise errors.ArgError("periods length mismatch")
        # reorder is identity on TPU: device order already encodes ICI
        # adjacency (see package docstring); keep the flag for API parity.
        self.reorder = bool(reorder)
        # rank -> coords (row-major), coords -> rank
        self._coords = np.stack(
            np.unravel_index(np.arange(n), self.dims), axis=1
        ).astype(np.int32)
        # memoized static tables (built on demand, reused across traces)
        self._shift_cache: dict[tuple[int, int], tuple[list, list]] = {}
        self._neighbor_table: list[list[int]] | None = None

    # -- introspection (MPI_Cartdim_get / MPI_Cart_get) -------------------

    def coords(self, rank: int) -> tuple[int, ...]:
        """MPI_Cart_coords (``topo_base_cart_coords.c``)."""
        if not 0 <= rank < len(self._coords):
            raise errors.RankError(f"rank {rank} out of range")
        return tuple(int(c) for c in self._coords[rank])

    def rank_of(self, coords: Sequence[int]) -> int:
        """MPI_Cart_rank; periodic dims wrap, non-periodic out-of-range is
        an error (``topo_base_cart_rank.c``)."""
        if len(coords) != self.ndims:
            raise errors.ArgError("coords length mismatch")
        fixed = []
        for c, d, p in zip(coords, self.dims, self.periods):
            c = int(c)
            if p:
                c %= d
            elif not 0 <= c < d:
                raise errors.RankError(
                    f"coordinate {c} out of range for non-periodic dim {d}"
                )
            fixed.append(c)
        return int(np.ravel_multi_index(fixed, self.dims))

    # -- shift (MPI_Cart_shift) ------------------------------------------

    def shift(self, dim: int, disp: int = 1
              ) -> tuple[list[int], list[int]]:
        """Per-rank (rank_source, rank_dest) lists; -1 is MPI_PROC_NULL
        (``topo_base_cart_shift.c``).  Vectorized and memoized: tables are
        static per topology, so traces pay a dict lookup, not O(size)."""
        if not 0 <= dim < self.ndims:
            raise errors.ArgError(f"dim {dim} out of range")
        cached = self._shift_cache.get((dim, disp))
        if cached is not None:
            return list(cached[0]), list(cached[1])  # copies: cache is live

        def moved(delta: int) -> list[int]:
            c = self._coords.astype(np.int64).copy()
            c[:, dim] += delta
            d = self.dims[dim]
            if self.periods[dim]:
                c[:, dim] %= d
                valid = np.ones(len(c), dtype=bool)
            else:
                valid = (c[:, dim] >= 0) & (c[:, dim] < d)
                c[:, dim] = np.clip(c[:, dim], 0, d - 1)
            ranks = np.ravel_multi_index(c.T, self.dims)
            return list(np.where(valid, ranks, -1).astype(int))

        result = (moved(-disp), moved(disp))  # (sources, dests)
        self._shift_cache[(dim, disp)] = result
        return list(result[0]), list(result[1])

    def shift_exchange(self, x, dim: int, disp: int = 1):
        """Traced: every rank sends `x` to its +disp neighbor along `dim`
        and returns what arrives from its -disp neighbor (zeros at a
        non-periodic boundary).  The MPI_Cart_shift+MPI_Sendrecv idiom as a
        single collective-permute."""
        _, dst = self.shift(dim, disp)
        return self.comm.permute(x, dst)

    # -- sub-grids (MPI_Cart_sub) ----------------------------------------

    def sub(self, remain_dims: Sequence[bool], name: str | None = None):
        """Split into sub-communicators keeping `remain_dims` dims
        (``topo_base_cart_sub.c``).  Returns (comm, topo): one partitioned
        communicator whose groups are the sub-grids, each group ordered
        row-major over the kept dims, plus the kept-dims topology."""
        if len(remain_dims) != self.ndims:
            raise errors.ArgError("remain_dims length mismatch")
        keep = [i for i, k in enumerate(remain_dims) if k]
        drop = [i for i, k in enumerate(remain_dims) if not k]
        if not keep:
            raise errors.ArgError("must keep at least one dim")
        colors, keys = [], []
        for rank in range(len(self._coords)):
            c = self._coords[rank]
            drop_coords = tuple(int(c[i]) for i in drop)
            keep_coords = tuple(int(c[i]) for i in keep)
            color = 0 if not drop else int(np.ravel_multi_index(
                drop_coords, [self.dims[i] for i in drop]
            ))
            key = int(np.ravel_multi_index(
                keep_coords, [self.dims[i] for i in keep]
            ))
            colors.append(color)
            keys.append(key)
        sub = self.comm.split(colors, keys, name=name)
        topo = CartTopology.__new__(CartTopology)
        topo.comm = sub
        topo.dims = tuple(self.dims[i] for i in keep)
        topo.ndims = len(keep)
        topo.periods = tuple(self.periods[i] for i in keep)
        topo.reorder = False
        nsub = int(np.prod(topo.dims))
        topo._coords = np.stack(
            np.unravel_index(np.arange(nsub), topo.dims), axis=1
        ).astype(np.int32)
        topo._shift_cache = {}
        topo._neighbor_table = None
        return sub, topo

    # -- neighbor lists for neighbor collectives --------------------------

    def neighbor_ranks(self, rank: int) -> list[int]:
        """Ordered neighbors of `rank` for MPI_Neighbor_* on a cartesian
        communicator: for each dim, the -1 then +1 neighbor (the order
        MPI-3.1 §7.6 fixes); -1 = MPI_PROC_NULL."""
        if self._neighbor_table is None:
            shifts = [self.shift(d, 1) for d in range(self.ndims)]
            self._neighbor_table = [
                [t[r] for src_dst in shifts for t in src_dst]
                for r in range(len(self._coords))
            ]
        return list(self._neighbor_table[rank])

    # cartesian neighbor lists are symmetric: slot k both sends to and
    # receives from the k-th neighbor (MPI-3.1 §7.6 fixed order)
    def out_neighbors(self, rank: int) -> list[int]:
        return self.neighbor_ranks(rank)

    def in_neighbors(self, rank: int) -> list[int]:
        return self.neighbor_ranks(rank)

    @property
    def degree(self) -> int:
        return 2 * self.ndims

    def __repr__(self) -> str:  # pragma: no cover
        return (f"CartTopology(dims={self.dims}, periods={self.periods}, "
                f"comm={self.comm.name})")
