"""Headline benchmark: flagship train-step throughput through the framework
vs the identical step written in plain JAX (no framework layer).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline semantics: the reference publishes no numbers (BASELINE.md), so
the baseline is the strongest available stand-in — the same training step
with every framework collective replaced by a raw lax.psum.  A value >= 1.0
means the MPI-model layer (communicators, comm_select dispatch, tuned
decisions, f/g AD wrappers) costs nothing over hand-written JAX; that is the
claim being benchmarked.  On multi-device hosts the collectives are real; on
one chip they lower to no-ops but the full dispatch path still runs.
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import zhpe_ompi_tpu as zmpi
    from zhpe_ompi_tpu.models import transformer as tfm

    devs = jax.devices()
    n = len(devs)
    tp = 2 if n % 2 == 0 else 1
    dp = n // tp
    mesh = Mesh(np.asarray(devs[: dp * tp]).reshape(dp, tp), ("dp", "tp"))
    dp_comm = zmpi.Communicator(mesh, "dp", name="bench_dp")
    tp_comm = zmpi.Communicator(mesh, "tp", name="bench_tp") if tp > 1 else None

    on_tpu = devs[0].platform not in ("cpu",)
    if on_tpu:
        cfg = tfm.Config(
            vocab=8192, d_model=1024, n_heads=16, d_ff=4096, n_layers=4,
            seq=512, dtype=jnp.bfloat16,
        )
        batch = 8 * dp
        iters = 20
    else:
        cfg = tfm.Config(
            vocab=256, d_model=128, n_heads=8, d_ff=512, n_layers=2,
            seq=128, dtype=jnp.float32,
        )
        batch = 2 * dp
        iters = 5

    r = np.random.default_rng(0)
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))
    targets = jnp.asarray(r.integers(0, cfg.vocab, (batch, cfg.seq)))

    def bench_step(step, specs):
        sharded = {
            k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()
        }
        dspec = NamedSharding(mesh, P("dp"))
        tok = jax.device_put(tokens, dspec)
        tgt = jax.device_put(targets, dspec)
        ps, loss = step(sharded, tok, tgt)  # compile
        for _ in range(3):  # warm caches/threads
            ps, loss = step(ps, tok, tgt)
        jax.block_until_ready(loss)
        best = float("inf")
        for _ in range(3):  # best-of-3 timing windows
            t0 = time.perf_counter()
            for _ in range(iters):
                ps, loss = step(ps, tok, tgt)
            jax.block_until_ready(loss)
            best = min(best, (time.perf_counter() - t0) / iters)
        return batch * cfg.seq / best  # tokens/sec

    # framework path
    step_fw, specs = tfm.make_train_step(cfg, mesh, dp_comm, tp_comm)
    fw_tps = bench_step(step_fw, specs)

    # plain-JAX baseline: identical math, raw lax.psum collectives
    from jax import lax

    def make_plain_step():
        from zhpe_ompi_tpu.parallel import grad as gradmod

        class RawComm:
            def __init__(self, axis):
                self.axis = axis

            def allreduce(self, x, op):
                return lax.psum(x, self.axis)

        raw_tp = RawComm("tp") if tp > 1 else None
        raw_dp = RawComm("dp")

        dp_sz = dp
        tp_sz = tp
        param_specs = specs

        def spmd_step(p, tok, tgt):
            def local_loss(pp):
                return tfm.loss_fn(pp, tok, tgt, cfg, raw_tp)

            loss, grads = jax.value_and_grad(local_loss)(p)
            synced = {}
            replicated = {"embed", "lnf", "ln1", "ln2"}
            for name, g in grads.items():
                g = lax.psum(g, "dp") / dp_sz
                if name in replicated and raw_tp is not None:
                    g = lax.psum(g, "tp") / tp_sz
                synced[name] = g
            loss = lax.psum(loss, "dp") / dp_sz
            if raw_tp is not None:
                loss = lax.psum(loss, "tp") / tp_sz
            new_p = jax.tree.map(
                lambda a, g: (a - 1e-2 * g).astype(a.dtype), p, synced
            )
            return new_p, loss

        return jax.jit(
            jax.shard_map(
                spmd_step, mesh=mesh,
                in_specs=(param_specs, P("dp"), P("dp")),
                out_specs=(param_specs, P()),
                check_vma=False,
            )
        )

    plain_tps = bench_step(make_plain_step(), specs)

    print(json.dumps({
        "metric": "train_step_throughput",
        "value": round(fw_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(fw_tps / plain_tps, 4),
    }))


if __name__ == "__main__":
    main()
