"""TCP transport — the btl/tcp / DCN analog of the host plane.

The reference reaches remote nodes through ``opal/mca/btl/tcp`` (5.3k LoC:
endpoint address exchange via the modex, a listening socket per proc, lazy
connection establishment, length-framed sends drained by the progress
engine).  On TPU pods the *device* plane crosses hosts through ICI/DCN
inside XLA; what still needs a wire is the host plane — control messages,
dpm, shmem bookkeeping, file coordination.  This module is that wire:

- **modex**: rank 0 is the rendezvous point (the PMIx server analog);
  every rank connects, publishes its listen address, and receives the
  address book (cf. the business-card exchange in ompi_mpi_init.c:667).
- **endpoints**: one listening socket per proc, full-mesh connections
  established lazily on first send and cached (btl_tcp_endpoint.c shape).
- **framing**: 4-byte length + DSS-packed (src, tag, cid, seq, payload) —
  the DSS buffer is the wire format, so anything the out-of-band plane
  can represent travels as-is.
- **matching**: incoming frames feed the same matching engine the local
  universe uses — transport and semantics stay decoupled exactly as
  BTL/PML are.
- **selection**: per-peer transport dispatch at the send seam — the
  decision ladder is self → sm → tcp: rank-to-self takes the loopback
  shortcut, a same-boot peer that advertised a shared-memory segment
  rides the mmap ring (``pt2pt/sm.py``, chosen while ``sm_priority``
  exceeds ``tcp_priority``), everything else — remote hosts, mixed
  ``sm=0`` pairs, respawned rejoiners, dpm bridges, and the whole FT
  control family — rides the sockets below.

``TcpProc`` mirrors :class:`~zhpe_ompi_tpu.pt2pt.universe.RankContext``'s
API (send/recv/probe/sendrecv/barrier), so everything built on rank
contexts — ft logging, crcp bookmarks, shmem collectives — runs over real
sockets unchanged.  Tests drive N procs over localhost; multi-host runs
pass the coordinator's address, the role `jax.distributed.initialize`'s
coordinator plays for the device plane.
"""

from __future__ import annotations

import collections
import itertools
import os
import queue
import random
import socket
import struct
import threading
import time
import weakref
from typing import Any

import numpy as np

from ..coll.host import HostCollectives
from ..coll.nbc import NonblockingCollectives
from ..core import errhandler as errh
from ..core import errors
from ..ft import ulfm
from ..mca import output as mca_output
from ..mca import var as mca_var
from ..runtime import flightrec
from ..runtime import spc
from ..runtime import ztrace
from ..utils import dss
from ..utils import lockdep
from . import engine_mux
from . import matching
from . import overlay
from . import sm as sm_mod
from .matching import ANY_SOURCE, ANY_TAG, Envelope

_stream = mca_output.open_stream("btl_tcp")

_LEN = struct.Struct("<I")

mca_var.register(
    "tcp_eager_limit", 1 << 20,
    "Serialized size (bytes) above which TCP sends use RTS/CTS rendezvous "
    "instead of eager delivery (bounds receiver-side unexpected-queue "
    "memory, the ob1 eager_limit contract on the wire plane)",
    type=int,
)
mca_var.register(
    "tcp_zero_copy_min", 0,
    "Array payload size (bytes) at/above which contiguous ndarray "
    "payloads ride the out-of-band zero-copy frame path (dss.pack_frames "
    "memoryview segments over sendmsg); 0 = every contiguous array",
    type=int,
)
mca_var.register(
    "tcp_priority", 20,
    "Endpoint-selection priority of the tcp transport (btl_tcp_priority "
    "shape): a same-host peer rides the shared-memory ring only while "
    "sm_priority exceeds this — raise it above sm_priority to force the "
    "wire path per-pair without tearing the rings down",
    type=int,
)
mca_var.register(
    "tcp_rndv_push_workers", 4,
    "Rendezvous data-push executor threads per proc: a burst of large "
    "sends queues its CTS-released pushes on this bounded pool instead "
    "of spawning one thread per transfer",
    type=int,
)

# category derivation (tools/mpit.py): the wire plane's vars and
# counters — tcp_*, btl_tcp_*, rndv_* — are ONE family
mca_var.register_family("tcp")
mca_var.register_family("btl_tcp", "tcp")
mca_var.register_family("rndv", "tcp")

# sendmsg gathers header+segments in one syscall; platforms without it
# (or a socket object that declines) fall back to sequential sendall
_HAS_SENDMSG = hasattr(socket.socket, "sendmsg")
# stay well under IOV_MAX (typically 1024) per sendmsg call
_IOV_BATCH = 256

# rendezvous control channels (outside the user cid space)
_RNDV_CTS_CID = 0x7FFA
_RNDV_DATA_CID = 0x7FF9
# wire sentinel of an RTS announce (first element of a 4-tuple payload;
# the remaining elements are sender_rank, rndv_id, nbytes)
_RTS_MARK = "__zmpi_rndv_rts__"
# fair-share rendezvous drain: a channel yields its push-pool worker
# after this many items whenever another channel is queued behind it
_PUSH_RR_QUANTUM = 8


# eager/rendezvous switch sizing — the shared estimator (one
# implementation for the transport switch AND the han SPC accounting)
from ..utils.payload import payload_size_estimate as _payload_size  # noqa: E402


def _byte_views(segments) -> list[memoryview]:
    """Normalize a segment list to flat uint8 memoryviews (sendmsg wants
    byte buffers; ndarray data views carry their own shape/format)."""
    views = []
    for seg in segments:
        v = seg if isinstance(seg, memoryview) else memoryview(seg)
        if v.format != "B" or v.ndim != 1:
            v = v.cast("B")
        views.append(v)
    return views


def _send_frame(sock: socket.socket, payload) -> int:
    """Emit one length-framed message from `payload` — bytes, or a
    sequence of buffer segments sent VECTORED via ``socket.sendmsg``
    (no header+body concatenation, no frame-assembly copy; the btl
    iovec discipline).  Returns — and counts in ``tcp_bytes_sent`` —
    the actual on-wire byte total including the 4-byte length header."""
    if isinstance(payload, (bytes, bytearray, memoryview)):
        segments = (payload,)
    else:
        segments = payload
    views = _byte_views(segments)
    total = sum(v.nbytes for v in views)
    bufs = [memoryview(_LEN.pack(total))]
    bufs += [v for v in views if v.nbytes]
    if _HAS_SENDMSG:
        while bufs:
            n = sock.sendmsg(bufs[:_IOV_BATCH])
            # advance past what the kernel took (a short write leaves a
            # suffix of the iovec; blocking sockets never return 0)
            while n:
                head = bufs[0]
                if n >= head.nbytes:
                    n -= head.nbytes
                    bufs.pop(0)
                else:
                    bufs[0] = head[n:]
                    n = 0
    else:  # pragma: no cover - every target platform has sendmsg
        for v in bufs:
            sock.sendall(v)
    spc.record("tcp_bytes_sent", total + _LEN.size)
    return total + _LEN.size


def _recv_exact_into(sock: socket.socket, n: int,
                     idle_retry: bool = False) -> bytearray | None:
    """Read exactly n bytes into ONE preallocated writable buffer via
    ``recv_into`` — no accumulate-then-copy; the returned bytearray is
    dedicated to this frame, so dss.unpack_from may alias it."""
    buf = bytearray(n)
    if n == 0:
        return buf
    view = memoryview(buf)
    got = 0
    while got < n:
        try:
            k = sock.recv_into(view[got:])
        except socket.timeout:
            if idle_retry and got == 0:
                # a QUIET connection is not a dead one: the drain's
                # steady state must outlive any socket timeout.  A
                # timeout with PARTIAL bytes read still raises — a peer
                # wedged mid-frame would desync the length framing.
                continue
            raise
        if not k:
            return None
        got += k
    return buf


def _recv_frame(sock: socket.socket,
                idle_retry: bool = False) -> bytearray | None:
    header = _recv_exact_into(sock, _LEN.size, idle_retry=idle_retry)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    body = _recv_exact_into(sock, length)
    if body is not None:
        spc.record("tcp_bytes_recvd", length + _LEN.size)
    return body


class _Backoff:
    """Exponential connect backoff with deterministic per-caller jitter,
    bounded by a total budget — shared by the modex rendezvous and lazy
    endpoint establishment so a slow-starting peer is retried patiently
    (no thundering herd) but never past the deadline."""

    START, CAP = 0.01, 0.5

    def __init__(self, budget: float, seed: int):
        self.stop_at = time.monotonic() + budget
        self.delay = self.START
        self._jitter = random.Random(seed)

    def expired(self, lookahead: float = 0.0) -> bool:
        return time.monotonic() + lookahead >= self.stop_at

    def sleep(self) -> None:
        time.sleep(min(
            self.delay * (0.5 + self._jitter.random()),
            max(0.0, self.stop_at - time.monotonic()),
        ))
        self.delay = min(self.delay * 2, self.CAP)


class _LoopbackFallback(Exception):
    """Payload type outside the fast-copy universe: take the full
    serialize/deserialize cycle (which also owns the error surface for
    unpackable types)."""


def _loopback_copy(obj: Any, _depth: int = 0):
    """Single defensive copy for rank-to-self delivery, with the SAME
    type mapping the DSS round trip applies (tuple stays tuple,
    bytearray lands as bytes, numpy scalars as 0-d arrays) — the
    receiver must see the pre-mutation value even if the sender reuses
    its buffer immediately, but nothing needs to be serialized to
    cross a process boundary that isn't there."""
    if obj is None or isinstance(obj, (bool, str, bytes)):
        return obj  # immutable: by-reference IS value semantics
    if isinstance(obj, float):
        # np.float64 subclasses float and DSS delivers it as plain float
        return obj if type(obj) is float else float(obj)
    if isinstance(obj, int):
        return obj if type(obj) is int else int(obj)  # IntEnum et al.
    if isinstance(obj, bytearray):
        return bytes(obj)
    if isinstance(obj, np.ndarray):
        # ascontiguousarray already materializes a fresh array for
        # non-contiguous input — exactly one copy either way
        return np.ascontiguousarray(obj) \
            if not obj.flags.c_contiguous else obj.copy()
    if isinstance(obj, np.generic):
        return np.asarray(obj).copy()
    if _depth >= 16:
        raise _LoopbackFallback  # absurd nesting: let dss arbitrate
    if isinstance(obj, (list, tuple)):
        return type(obj)(_loopback_copy(o, _depth + 1) for o in obj)
    if isinstance(obj, dict):
        return {
            _loopback_copy(k, _depth + 1): _loopback_copy(v, _depth + 1)
            for k, v in obj.items()
        }
    raise _LoopbackFallback


class _PushPool:
    """Bounded rendezvous-push executor: CTS-released bulk pushes queue
    here instead of spawning one thread per transfer, so a burst of
    large sends cannot grow the thread count without bound (the
    reference bounds its rndv pipeline by the send-request freelist).
    Workers start lazily up to the cap and exit at close()."""

    def __init__(self, name: str, max_workers: int):
        self._q: queue.Queue = queue.Queue()
        self._threads: list[threading.Thread] = []
        self._lock = lockdep.lock("tcp._PushPool._lock")
        self._idle = 0
        self._closed = False
        self._name = name
        self._max = max(1, max_workers)

    def submit(self, fn) -> None:
        with self._lock:
            if self._closed:
                # post-close CTS (late-matching peer): a one-shot thread
                # completes the transfer — TRACKED, so the leak gate
                # still sees it if it wedges on a dead peer
                t = threading.Thread(
                    target=fn, daemon=True, name=f"{self._name}-late"
                )
                self._threads.append(t)
                t.start()
                return
            self._q.put(fn)
            if self._idle == 0 and len(self._threads) < self._max:
                t = threading.Thread(
                    target=self._worker, daemon=True,
                    name=f"{self._name}-{len(self._threads)}",
                )
                self._threads.append(t)
                t.start()

    def _worker(self) -> None:
        while True:
            with self._lock:
                self._idle += 1
            fn = self._q.get()  # blocking; close() wakes via sentinel
            with self._lock:
                self._idle -= 1
            if fn is None:
                return  # close() sentinel
            try:
                fn()
            # zlint: disable=ZL004 -- _push_rndv catches every escape itself and completes the request errored (PR 7); this is the worker's don't-die backstop
            except Exception:  # noqa: BLE001 - push_data logs its own
                pass

    def close(self, timeout: float) -> None:
        with self._lock:
            first = not self._closed
            self._closed = True
            threads = list(self._threads)
        if first:
            # one sentinel per worker: each consumes exactly one and
            # exits once the queued pushes ahead of it drain
            for t in threads:
                if t.is_alive():
                    self._q.put(None)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))

    def backlog(self) -> int:
        """Queued-but-unclaimed work items — the fair-share rotation
        reads this: a channel drain yields its worker only when some
        OTHER channel is actually waiting for one."""
        return self._q.qsize()

    def alive_threads(self) -> list[threading.Thread]:
        with self._lock:
            return [t for t in self._threads if t.is_alive()]


# every proc's pool, weakly: the conftest leak gate asserts each pool
# drained at close() without keeping closed procs alive
_live_push_pools: weakref.WeakSet = weakref.WeakSet()


def live_push_threads() -> list[str]:
    """Names of rendezvous-push worker threads still alive across all
    (weakly tracked) procs — the test-suite hygiene gate's view."""
    return [
        t.name
        for pool in list(_live_push_pools)
        for t in pool.alive_threads()
    ]


class _OutChannel:
    """Per-destination deferred-send FIFO — the send side of the
    nonblocking progress engine.  ``isend`` enqueues its work here and
    returns; push-pool workers drain each channel strictly in order, so
    deferred frames to one peer can never reorder among themselves (the
    per-source FIFO the matching engine assumes), and blocking sends
    FENCE on the channel before writing the socket inline (ordering
    across both send paths).  ``draining`` marks the single worker that
    owns the queue; the empty→non-empty transition submits one."""

    __slots__ = ("lock", "queue", "draining")

    def __init__(self):
        self.lock = lockdep.lock("tcp._OutChannel.lock")
        # items: (work, request, finish) — `work()` performs the send;
        # `finish` marks the item whose success completes the request
        # (an RTS item carries its rendezvous request only for the
        # poisoned-while-parked skip; the DATA push completes it)
        self.queue: collections.deque = collections.deque()
        self.draining = False

    def busy(self) -> bool:
        with self.lock:
            return bool(self.queue) or self.draining


# every proc, weakly: the hygiene gate walks CLOSED procs asserting no
# incomplete deferred SendRequest and no orphaned parked-rndv
# descriptor survived teardown (open procs legitimately hold both)
_live_procs: weakref.WeakSet = weakref.WeakSet()


def live_incomplete_send_requests() -> list[str]:
    """Deferred SendRequests still incomplete on CLOSED procs — the
    test-suite hygiene gate's view (close() drains the in-flight set
    bounded, then completes leftovers errored; sever() abandons them
    errored immediately — either way nothing may stay incomplete)."""
    out = []
    for proc in list(_live_procs):
        if not proc._closed.is_set():
            continue
        for req in list(proc._inflight):
            if not req.done:
                out.append(f"rank{proc.rank}: incomplete deferred send")
    return out


def orphaned_rndv_descriptors() -> list[str]:
    """Parked rendezvous descriptors left on CLOSED procs — the gate's
    view of the park table (a descriptor nobody will ever push pins the
    caller's buffers forever)."""
    out = []
    for proc in list(_live_procs):
        if not proc._closed.is_set():
            continue
        with proc._rndv_lock:
            ids = sorted(proc._pending_rndv)
        out += [f"rank{proc.rank}: parked rndv id={i}" for i in ids]
    return out


def _wire_queue_depth(key: str) -> int:
    """Matching-queue depth across every OPEN wire proc in this
    process — the state-pvar twin of universe.py's thread-plane
    readers, so the metrics publisher's snapshot carries live queue
    depths for socket ranks too."""
    total = 0
    for proc in list(_live_procs):
        if proc._closed.is_set():
            continue
        total += proc.engine.stats()[key]
    return total


_wire_pvars_registered = False


def _register_wire_pvars() -> None:
    global _wire_pvars_registered
    if _wire_pvars_registered:
        return
    from ..tools import mpit

    mpit.register_pvar(
        "tcp_posted_recvs", lambda: _wire_queue_depth("posted"),
        klass=mpit.PVAR_STATE,
        description="posted receives across this process's open wire "
                    "procs",
    )
    mpit.register_pvar(
        "tcp_unexpected_msgs", lambda: _wire_queue_depth("unexpected"),
        klass=mpit.PVAR_STATE,
        description="unexpected-queue depth across this process's open "
                    "wire procs",
    )
    _wire_pvars_registered = True


class TcpProc(errh.HasErrhandler, ulfm.UlfmEndpointAPI, HostCollectives,
              NonblockingCollectives):
    """One process's endpoint in a TCP universe of `size` ranks.
    Collectives come from :class:`~zhpe_ompi_tpu.coll.host.HostCollectives`
    and :class:`~zhpe_ompi_tpu.coll.nbc.NonblockingCollectives`, so
    socket-connected (DCN) ranks bcast/allreduce/iallreduce exactly like
    thread ranks — the coll-rides-the-PML layering of the reference.

    Construction is collective: every rank calls with the same coordinator
    address; rank 0 binds it as the rendezvous socket, the rest connect
    with retry.  `host` is this rank's reachable address."""

    def __init__(self, rank: int, size: int,
                 coordinator: tuple[str, int] = ("127.0.0.1", 0),
                 host: str = "127.0.0.1", timeout: float = 30.0,
                 on_coordinator_bound=None,
                 external_coordinator: bool = False,
                 ft: bool = False,
                 rejoin_book: list | None = None,
                 sm: bool | None = None,
                 sm_boot_id: str | None = None,
                 sm_numa_id: str | None = None,
                 pmix: "tuple[str, int] | str | None" = None,
                 namespace: str = "default",
                 rejoin: bool = False,
                 rejoin_gen: int = 0,
                 rejoin_ranks: "list[int] | None" = None,
                 metrics: bool | None = None,
                 trace: bool | None = None,
                 live_ranks: "list[int] | None" = None):
        if size < 1:
            raise errors.ArgError("size must be >= 1")
        # elastic membership (the DVM resize contract): the universe is
        # `size` slots but only `live_ranks` started — the rest wire up
        # as pre-acknowledged departures (the orderly-BYE state), so
        # collectives ride a shrunken endpoint over the live set and a
        # later grow FT_JOINs an absent slot exactly like a recovery
        # window's replacement
        self._live_ranks: frozenset[int] | None = None
        if live_ranks is not None:
            live = frozenset(int(r) for r in live_ranks)
            if live != frozenset(range(size)):
                if rank not in live:
                    raise errors.ArgError(
                        f"live_ranks must include this rank ({rank})")
                if not live <= frozenset(range(size)):
                    raise errors.ArgError(
                        "live_ranks outside the universe size")
                if pmix is None or not ft:
                    raise errors.ArgError(
                        "elastic membership (live_ranks a proper "
                        "subset) needs the store-served wire-up and "
                        "fault tolerance: pass pmix=(host, port) and "
                        "ft=True (the ZMPI_ELASTIC_LIVE contract)")
                self._live_ranks = live
        # metrics plane: explicit opt-in (ctor arg) or the ZMPI_METRICS
        # environment contract a DVM job launched with metrics=True
        # exports.  Publishing needs a store — an explicit metrics=True
        # without one is a caller contract error, an env-driven request
        # degrades loudly (the env may be fleet-global).
        if metrics is None:
            metrics = os.environ.get("ZMPI_METRICS", "") not in ("", "0")
            env_metrics = True
        else:
            metrics = bool(metrics)
            env_metrics = False
        if metrics and pmix is None:
            if not env_metrics:
                raise errors.ArgError(
                    "metrics=True publishes through the PMIx store: "
                    "pass pmix=(host, port) (the ZMPI_PMIX contract)"
                )
            mca_output.emit(
                _stream,
                "rank %s: ZMPI_METRICS set but no PMIx store to "
                "publish into; metrics plane disabled", rank,
            )
            metrics = False
        self._metrics_on = metrics
        # tracing plane: rides the metrics publisher (the trace buffer
        # publishes as trace:<job>:<rank> next to the snapshots), so
        # trace needs metrics needs a store.  Explicit trace=True
        # without the metrics plane is a caller contract error; the
        # env-driven ZMPI_TRACE request degrades loudly.
        if trace is None:
            trace = os.environ.get("ZMPI_TRACE", "") not in ("", "0")
            env_trace = True
        else:
            trace = bool(trace)
            env_trace = False
        if trace and not metrics:
            if not env_trace:
                raise errors.ArgError(
                    "trace=True publishes span buffers through the "
                    "metrics publisher: pass metrics=True and "
                    "pmix=(host, port) (the ZMPI_TRACE contract)"
                )
            mca_output.emit(
                _stream,
                "rank %s: ZMPI_TRACE set but the metrics plane is off; "
                "tracing plane disabled", rank,
            )
            trace = False
        self._trace_on = trace
        self._metrics_pub: spc.MetricsPublisher | None = None
        if (rejoin_book is not None or rejoin) and not ft:
            raise errors.ArgError(
                "rejoin_book (respawn into an existing job) requires ft=True"
            )
        if rejoin and pmix is None:
            raise errors.ArgError(
                "rejoin=True re-modexes through the name-served PMIx "
                "store: pass pmix=(host, port) (the ZMPI_PMIX contract)"
            )
        # PMIx-served wire-up (the runtime-plane store of runtime/pmix.py):
        # the modex rides put/commit/fence/get verbs against a resident
        # server instead of the per-job rendezvous coordinator, and a
        # respawned rank (rejoin=True) fetches the name-served address
        # book from the same store — no in-process survivor handoff.
        if isinstance(pmix, str):
            pmix_host, pmix_port = pmix.rsplit(":", 1)
            pmix = (pmix_host, int(pmix_port))
        self._pmix_addr: tuple[str, int] | None = \
            (pmix[0], int(pmix[1])) if pmix is not None else None
        self._pmix_ns = str(namespace)
        # batched-recovery window metadata (ZMPI_REJOIN_GEN/_RANKS): the
        # ranks respawned ALONGSIDE us this window, whose store cards we
        # must read at the window's bumped generation — the corpse's
        # generation-old card would satisfy a plain get and strand both
        # replacements dialing each other's dead addresses
        self._rejoin_gen = int(rejoin_gen)
        self._rejoin_ranks = frozenset(
            int(r) for r in (rejoin_ranks or ()))
        self.rank = rank
        self.size = size
        # ULFM state precedes the accept loop: drain threads consult it
        self.ft_state = ulfm.FailureState(size) if ft else None
        self._ft_dead = False
        self._detector: ulfm.RingDetector | None = None
        self.engine = matching.make_matching_engine()
        self._seq = itertools.count()
        self._rndv_ids = itertools.count(1)
        # rndv_id -> parked data-frame segments.  send() parks COPIES
        # (its buffer-reuse contract holds at return); isend parks the
        # DESCRIPTOR — the caller's own buffers, pinned by the
        # SendRequest until the CTS-released push completes.
        self._pending_rndv: dict[int, list] = {}
        # rndv_id -> (dest, SendRequest-or-None): who the transfer is
        # for (peer death poisons it) and which request its push
        # completes (None for blocking sends)
        self._rndv_meta: dict[int, tuple[int, Any]] = {}
        # rndv_id -> parent send-span sid, populated only while the
        # tracing plane is armed (the CTS-released push leg records a
        # PUSH span parented on the originating send span); entries
        # drop with their transfer
        self._rndv_trace: dict[int, int] = {}
        # witnessed under lockdep: THE seam zlint ZL002 covers
        # statically and PR 7 paid three review rounds to order
        self._rndv_lock = lockdep.lock("tcp.TcpProc._rndv_lock")
        # deferred-send progress engine: per-destination FIFO channels
        # drained by the push-pool workers, plus the in-flight request
        # registry the hygiene gate inspects after close()
        self._out_channels: dict[int, _OutChannel] = {}
        self._out_lock = lockdep.lock("tcp.TcpProc._out_lock")
        self._inflight: weakref.WeakSet = weakref.WeakSet()
        self._push_pool = _PushPool(
            f"rndv-push-{rank}",
            int(mca_var.get("tcp_rndv_push_workers", 4)),
        )
        _live_push_pools.add(self._push_pool)
        # ONE multiplexed channel engine per proc replaces the accept
        # thread and every per-connection drain thread (the scale-out
        # fabric's thread/fd bound: readers are O(1) in connection
        # count); created with the listener below
        self._chan_engine: engine_mux.ChannelEngine | None = None
        self._flood_threads: list[threading.Thread] = []
        self._flood_lock = lockdep.lock("tcp.TcpProc._flood_lock")
        self._dup_conns: list[socket.socket] = []  # crossed-connect extras
        self._timeout = timeout
        self._conns: dict[int, socket.socket] = {}
        self._conn_lock = lockdep.lock("tcp.TcpProc._conn_lock")
        # guards the per-socket lock registry only
        self._send_lock = lockdep.lock("tcp.TcpProc._send_lock")
        self._sock_locks: weakref.WeakKeyDictionary = \
            weakref.WeakKeyDictionary()  # socket -> its framing lock
        self._closed = threading.Event()
        self._incoming_cv = threading.Condition()
        _live_procs.add(self)
        # shared-memory plane (btl/sm analog): create OUR inbound-ring
        # segment before the modex so the card can advertise a segment
        # that already exists — a peer that got the book can map it with
        # no handshake and no transport-switch reordering window.
        # Respawned (rejoin) ranks stay TCP: the C plane's "spawn joins
        # stay TCP" cohort contract — survivors scrub the joiner's card.
        self._sm_seg: sm_mod.SmSegment | None = None
        self._sm_senders: dict[int, sm_mod.SmSender | None] = {}
        self._sm_declined: set[int] = set()  # advertised sm, not ridden
        self._sm_lock = lockdep.lock("tcp.TcpProc._sm_lock")
        self._sm_boot = sm_boot_id or sm_mod.boot_token()
        # NUMA-domain token (hosts nest into domains): constructor
        # override for per-rank emulation, else the sm_numa_id MCA var
        # / sysfs derivation — advertised next to the pyshm card item
        self._sm_numa = (
            str(sm_numa_id).strip().replace(":", "_")[:64]
            if sm_numa_id else sm_mod.numa_token()
        )
        sm_on = bool(int(mca_var.get("sm", 1))) if sm is None else bool(sm)
        if sm_on and size > 1 and rejoin_book is None and not rejoin:
            try:
                self._sm_seg = sm_mod.SmSegment(
                    rank, size, on_frame=self._sm_incoming
                )
            except OSError as e:
                mca_output.emit(
                    _stream,
                    "rank %s: sm segment unavailable (%s); host plane "
                    "degrades to TCP", rank, e,
                )
        # rejoin handshake state: survivor JOIN_ACKs carrying their
        # collective/agreement counters + crash epoch (see _announce_join)
        self._join_cv = threading.Condition()
        self._join_acks: dict[int, tuple[int, int, int]] = {}

        try:
            # listening socket (btl_tcp's per-proc endpoint)
            self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind((host, 0))
            self._listener.listen(size + 4)
            self.address = self._listener.getsockname()

            self._chan_engine = engine_mux.ChannelEngine(f"tcp-r{rank}")
            self._chan_engine.add_listener(self._listener,
                                           self._on_accept)
            self._chan_engine.start()

            # modex: address-book exchange through the coordinator.
            # `on_coordinator_bound(addr)` fires on rank 0 after the rendezvous
            # socket is bound but BEFORE the blocking gather — the hook a
            # launcher uses to forward an ephemeral coordinator address to the
            # other ranks (prte forwarding the PMIx URI).  With a fixed,
            # pre-agreed port it is unnecessary.
            self._on_coordinator_bound = on_coordinator_bound
            # external_coordinator: a launcher hosts the rendezvous (the
            # PRRTE-hosts-the-PMIx-server shape) — rank 0 joins as a client
            # instead of binding the coordinator address itself
            self._external_coordinator = external_coordinator
            if rejoin and rejoin_book is None:
                # name-served rejoin: the survivors' cards live in the
                # job's PMIx namespace — fetch the book from the store
                # and publish OUR fresh endpoint (generation-tagged: the
                # daemon bumped the namespace generation when it opened
                # this recovery window, so the new card is provably not
                # the corpse's)
                rejoin_book = self._pmix_rejoin_book(timeout)
            if rejoin_book is not None:
                # respawned rank: no modex rendezvous exists anymore —
                # adopt the survivors' address book with OUR fresh
                # endpoint in the old slot; the JOIN announce below
                # re-modexes the survivors.  Only the (host, port)
                # prefix is adopted: the survivors' pre-crash sm cards
                # point at rings whose peer half died with the old
                # incarnation, and rejoiners ride TCP anyway.
                self._peer_cards = [list(a[:2]) for a in rejoin_book]
                self.address_book = [tuple(a[:2]) for a in rejoin_book]
                self.address_book[rank] = tuple(self.address)
            elif self._pmix_addr is not None:
                self.address_book = self._modex_pmix(timeout)
            else:
                self.address_book = self._modex(coordinator, timeout)
            if self._live_ranks is not None:
                # absent slots are pre-acknowledged departures from the
                # first moment: named traffic to them classifies typed,
                # the detector ring skips them, shrink excludes them —
                # and a grow's FT_JOIN restores them like any rejoiner
                for r in range(size):
                    if r != rank and r not in self._live_ranks:
                        self.ft_state.mark_departed(r)
            mca_output.verbose(
                5, _stream, "rank %d up at %s; book=%s", rank, self.address,
                self.address_book,
            )
            _register_wire_pvars()
            if self._metrics_on:
                # rank-side metrics publisher: periodic generation-
                # tagged snapshots into the job's namespace, final
                # flush at close() — started after the modex so the
                # namespace provably exists
                self._metrics_pub = spc.MetricsPublisher(
                    self._pmix_addr, self._pmix_ns, rank,
                    trace=self._trace_on)
                self._metrics_pub.start()
            if ft:
                # peer death ⇒ ring teardown: the sm transport unmaps its
                # ring into a corpse the moment classification learns of it
                # (detector, transport error, notice flood, or goodbye)
                self.ft_state.add_failure_listener(self._sm_peer_dead)
                # peer death ⇒ typed completion of every parked isend
                # toward it (queued frames AND parked rndv descriptors):
                # a waitall must observe ProcFailed, never wedge
                self.ft_state.add_failure_listener(self._fail_inflight)
                if self._metrics_pub is not None:
                    # typed classification ⇒ this survivor's flight-
                    # recorder window ships to the store (the FT_CLASS
                    # event is already the ring's tail: FailureState
                    # records before it notifies listeners)
                    self.ft_state.add_failure_listener(
                        self._metrics_pub.on_classification)
                if rejoin_book is not None:
                    # announce BEFORE the detector starts: beats toward a
                    # survivor that has not yet swapped in the fresh
                    # endpoint would ride (and warm) a stale address
                    self._announce_join(timeout)
                # ring heartbeat detector over framed beats: this rank emits
                # to its nearest live predecessor, observes its nearest live
                # successor, floods suspicion (the ULFM detector shape)
                self._detector = ulfm.RingDetector(
                    rank, size, self.ft_state,
                    transport=ulfm.WireTransport(rank, size, self._ft_emit),
                    flood=self._ft_flood,
                    muted=lambda: self._ft_dead,
                    name=f"hb-tcp-{rank}",
                )
                self._detector.start()
        except BaseException:
            # a proc that never finished wiring up still owns a
            # mapped segment and a poll thread, and nobody will
            # ever call close() on a constructor that raised —
            # the zero-orphan/zero-leak lifecycle contract is
            # honored HERE, whichever construction step failed
            # (listener bind, accept start, modex, JOIN, detector)
            if self._metrics_pub is not None:
                self._metrics_pub.stop()
                self._metrics_pub = None
            if self._chan_engine is not None:
                self._chan_engine.close(1.0)
            if self._sm_seg is not None:
                self._sm_seg.close()
            raise

    def _frame_objs(self, tag: int, cid: int, seq: int, obj: Any,
                    tctx: "tuple[int, int, int] | None"
                    ) -> tuple:
        """The DSS frame-header values of one data frame.  While the
        tracing plane is armed (``tctx`` non-None) the compact
        ``(trace_id, parent_sid, seq)`` context rides as an OPTIONAL
        sixth value — receivers parent their deliver span on it; with
        tracing off the frame is the unchanged five-value shape, zero
        bytes of trace overhead on the wire (the A/B contract the OSU
        ``--trace`` row gates)."""
        if tctx is None:
            return (self.rank, tag, cid, seq, obj)
        # the header growth is the context's own encoding (pack() adds
        # one count varint byte for the single extra value)
        spc.record("trace_wire_context_bytes", len(dss.pack(tctx)) - 1)
        return (self.rank, tag, cid, seq, obj, tctx)

    def _trace_ingest(self, vals: list, transport: str) -> None:
        """Receiver half of the wire-propagated trace context: a
        six-value frame parents a DELIVER span (or, for a rendezvous
        RTS announce, the receiver-side CTS leg) on the sender's send
        span.  Malformed foreign contexts degrade silently — a drain
        loop must never raise over an optional tool field."""
        if len(vals) <= 5 or not ztrace.active:
            return
        ctx = ztrace.parse_wire_context(vals[5])
        if ctx is None:
            return
        src, tag, cid, _seq, payload = vals[:5]
        is_rts = (isinstance(payload, tuple) and len(payload) == 4
                  and payload[0] == _RTS_MARK)
        ztrace.instant(
            ztrace.CTS if is_rts else ztrace.DELIVER, self.rank,
            parent=ctx[1], trace=ctx[0], src=int(src), tag=int(tag),
            cid=int(cid), seq=int(ctx[2]), transport=transport,
        )

    def _framed_send(self, sock: socket.socket, frame) -> None:
        """Frames must not interleave on ONE socket, but independent
        sockets must not serialize behind each other — above all for the
        heartbeat path: a data send blocked on a wedged peer holding a
        global lock would starve this rank's own beats and get it
        falsely suspected.  Per-socket granularity is the contract.
        `frame` is bytes or a segment sequence (vectored framing)."""
        with self._send_lock:
            lock = self._sock_locks.get(sock)
            if lock is None:
                lock = self._sock_locks[sock] = lockdep.lock(
                    "tcp.TcpProc._sock_framing_lock")
        with lock:
            _send_frame(sock, frame)

    # -- shared-memory plane (btl/sm analog) ----------------------------

    def _sm_tx(self, dest: int) -> sm_mod.SmSender | None:
        """Per-peer transport selection, memoized: the sm ring when the
        peer advertised a same-boot segment AND sm outranks tcp
        (``sm_priority > tcp_priority``, the btl priority ladder), else
        None (TCP).  The decision is made ONCE per peer — a direction
        is all-ring or all-wire, so per-source FIFO needs no cross-
        transport sequence numbers (the reason the C plane routes a
        direction's ENTIRE main channel over one transport)."""
        if self._sm_seg is None:
            return None
        try:
            with self._sm_lock:
                if dest in self._sm_senders:
                    return self._sm_senders[dest]
                sender = self._sm_activate(dest)
                self._sm_senders[dest] = sender
                return sender
        except sm_mod.ConsumerStopped as e:
            # first contact raced the peer's sever/close: a STOPPED
            # consumer is never coming back — that is peer DEATH (the
            # sm twin of connection reset, PR 6's consumer-stopped
            # classification), NOT an unmappable-segment degradation,
            # so no silent-fallback count.  Classified OUTSIDE
            # _sm_lock: the death listener (_sm_peer_dead) re-takes it
            # to tear sm state down — classifying under the lock
            # self-deadlocks (found by this PR's kill-race testing;
            # the same-role nesting the lockdep class model skips).
            with self._sm_lock:
                self._sm_senders[dest] = None  # pinned to TCP
            if self.ft_state is not None:
                mca_output.verbose(
                    5, _stream,
                    "rank %s: first contact found rank %s's ring "
                    "consumer stopped (%s): classifying peer death",
                    self.rank, dest, e,
                )
                self._mark_transport_death(dest)
            else:
                mca_output.emit(
                    _stream,
                    "rank %s: sm segment of rank %s already stopped "
                    "(%s); pair degrades to TCP", self.rank, dest, e,
                )
                self._sm_declined.add(dest)
            return None

    def _sm_activate(self, dest: int) -> sm_mod.SmSender | None:
        if int(mca_var.get("sm_priority", 90)) <= \
                int(mca_var.get("tcp_priority", 20)):
            return None  # policy, not degradation: nothing to count
        cards = getattr(self, "_peer_cards", None)
        if cards is None or dest >= len(cards):
            return None
        card = sm_mod.parse_card(cards[dest])
        if card is None:
            return None  # peer runs sm=0 / is a C rank: intended TCP
        boot, name = card
        if boot != self._sm_boot:
            # mismatched boot id: the advertised /dev/shm namespace is
            # not provably ours — degrade loudly (counted per send)
            self._sm_declined.add(dest)
            return None
        # peer class decides the ring capacity the owner materializes:
        # a provably different NUMA domain makes this a leader-to-leader
        # pair (the han dleader exchange — segmented eager traffic);
        # unknown/absent/malformed tokens stay intra (full-size ring,
        # always correct)
        peer_numa = sm_mod.parse_numa(cards[dest])
        klass = sm_mod.CLASS_INTRA
        if peer_numa not in (None, sm_mod.NUMA_MALFORMED) \
                and peer_numa != self._sm_numa:
            klass = sm_mod.CLASS_LEADER
        try:
            sender = sm_mod.SmSender(name, src_rank=self.rank,
                                     dest_rank=dest, ring_class=klass)
        except sm_mod.ConsumerStopped:
            raise  # peer death, not degradation: _sm_tx classifies
            # it OUTSIDE _sm_lock (the death listener re-takes it)
        except (OSError, errors.MpiError) as e:
            mca_output.emit(
                _stream,
                "rank %s: sm segment of rank %s unmappable (%s); pair "
                "degrades to TCP", self.rank, dest, e,
            )
            self._sm_declined.add(dest)
            return None
        mca_output.verbose(
            5, _stream, "rank %d: sm ring to rank %d active (%s)",
            self.rank, dest, name,
        )
        return sender

    def _sm_send(self, smtx: sm_mod.SmSender, obj: Any, dest: int,
                 tag: int, cid: int, seq: int, nbytes: int,
                 tctx: "tuple[int, int, int] | None" = None,
                 objs: tuple | None = None) -> None:
        """One frame onto the peer's ring — the `_send_frame`-shaped
        seam of the sm plane.  Small frames pack their DSS header
        straight into the slot (``pack_frames_into``); larger ones take
        the fragment pipeline.  Ring backpressure (a full ring blocks
        HERE, with the peer's death classifying out of the spin) is the
        sm analog of the rendezvous receiver-memory bound: at most one
        message per direction ever occupies more than the ring."""
        state = self.ft_state
        closed = self._closed

        def abort():
            if closed.is_set():
                raise errors.InternalError(
                    f"sm send to rank {dest} on a closed proc"
                )
            if state is not None and state.is_failed(dest):
                raise errors.ProcFailed(
                    f"rank {dest} failed during an sm ring send",
                    failed_ranks=state.failed(),
                )

        abort()
        oob_min = int(mca_var.get("tcp_zero_copy_min", 0))
        deadline = time.monotonic() + self._timeout
        wire = None
        # direct (single-slot) only for SMALL frames: a mid-size message
        # is faster as a fragment pipeline — the peer's copy-out overlaps
        # our remaining copy-ins — so the pack-into fast path stops well
        # below the slot size
        if objs is None:
            objs = self._frame_objs(tag, cid, seq, obj, tctx)
        if nbytes + 512 <= min(smtx.slot_bytes, 32 << 10):
            wire = smtx.send_direct(objs, oob_min, deadline, abort)
            nfrags = 1
        if wire is None:
            header, oob = dss.pack_frames(*objs, oob_min=oob_min)
            wire, nfrags = smtx.send_frame(header, oob, deadline, abort)
        spc.record("sm_bytes_sent", wire)
        spc.record("sm_eager_sends" if nfrags == 1 else "sm_frag_sends",
                   1)

    def _sm_incoming(self, src_ring: int, frame: bytearray) -> None:
        """Poll-thread delivery: one assembled frame in a dedicated
        writable buffer — same contract as the socket drain loop, one
        matching engine for both transports."""
        try:
            vals = dss.unpack_from(frame)
            src, tag, cid, seq, payload = vals[:5]
        except (errors.MpiError, ValueError) as e:
            mca_output.emit(
                _stream,
                "rank %s: undecodable sm frame from ring %s: %s",
                self.rank, src_ring, e,
            )
            return
        if self.ft_state is not None and cid in (
            ulfm.FT_HB_CID, ulfm.FT_NOTICE_CID, ulfm.FT_REVOKE_CID,
            ulfm.FT_AGREE_PUB_CID, ulfm.FT_BYE_CID, ulfm.FT_DVM_CID,
        ):
            # the FT control family beats over TCP by design, with ONE
            # exception: the orderly-departure BYE of an sm peer rides
            # its ring so it trails every data frame already produced
            # (the per-direction FIFO the goodbye contract needs)
            self._ft_ctrl(cid, src, payload)
            return
        self._trace_ingest(vals, "sm")
        env = Envelope(src, tag, cid, seq)
        with self._incoming_cv:
            self.engine.incoming(env, payload)
            self._incoming_cv.notify_all()

    def _sm_peer_dead(self, rank: int, _cause: str) -> None:
        """Failure-listener hook (``FailureState.add_failure_listener``):
        a dead peer's consumer is never coming back — unmap our ring
        into it and pin the pair to TCP permanently (a respawned
        incarnation rides TCP per the cohort contract)."""
        with self._sm_lock:
            stale = self._sm_senders.get(rank)
            self._sm_senders[rank] = None
            self._sm_declined.discard(rank)
        if stale is not None:
            stale.close()

    def _sm_quiesce(self, deadline: float) -> None:
        """Bounded wait for peers to consume-and-deliver our outbound
        ring frames: the BYE goodbye below rides TCP, so without this
        it could overtake ring data still in flight and reclassify
        delivered messages as lost.  A peer whose poll loop already
        stopped can never drain — skip it."""
        with self._sm_lock:
            senders = [s for s in self._sm_senders.values()
                       if s is not None]
        for s in senders:
            # close-path drain: the CONSUMING peer needs the CPU more
            # than this poll does (ZL003) — 2 ms granularity merely
            # coarsens close by a hair
            while s.pending() and not s.peer_stopped() \
                    and time.monotonic() < deadline:
                time.sleep(0.002)

    def _sm_teardown(self) -> None:
        with self._sm_lock:
            senders = [s for s in self._sm_senders.values()
                       if s is not None]
            self._sm_senders = {r: None for r in self._sm_senders}
        for s in senders:
            s.close()
        if self._sm_seg is not None:
            self._sm_seg.close()

    # -- ULFM control plane ---------------------------------------------

    def _ft_emit(self, dest: int) -> None:
        """One heartbeat frame to `dest` (best-effort: a beat that cannot
        be delivered is evidence, not an error)."""
        if self._ft_dead or self._closed.is_set() \
                or self.ft_state.is_failed(dest):
            return
        frame = dss.pack(self.rank, 0, ulfm.FT_HB_CID, 0, b"")
        try:
            # short connect deadline: the detector thread must never park
            # in a connect retry, or our OWN beats stop and the observer
            # falsely suspects us
            sock = self._endpoint(dest, deadline=4 * self._detector.period
                                  if self._detector else 0.5)
            self._framed_send(sock, frame)
        except (OSError, errors.MpiError) as e:
            if isinstance(e, (ConnectionRefusedError, ConnectionResetError,
                              BrokenPipeError)):
                # connection refused/reset IS peer death, not a stall
                self._mark_transport_death(dest)

    def _flood(self, cid: int, payload: Any, name: str) -> None:
        """Best-effort ULFM control-plane flood to every live peer, on a
        one-shot daemon thread: no flooding caller — the detector loop
        (which must keep beating or its OWN observer falsely suspects
        it), a rank mid-recovery revoking a cid, a completing agreement
        — may stall behind serial connect deadlines to unreachable
        peers.  An undeliverable frame is dropped: the peer's own
        detector/recovery path covers it.  Threads are TRACKED so an
        orderly close() can flush them before tearing the wire down —
        an agreement announce racing its own rank's close would strand
        survivors in a round nobody can finish (sever(), a crash,
        still abandons them by design)."""
        t = threading.Thread(
            target=self._flood_sync, args=(cid, payload),
            daemon=True, name=f"{name}-{self.rank}",
        )
        with self._flood_lock:
            # registered BEFORE start so a concurrent close() cannot
            # miss it; the prune must therefore keep registered-but-
            # unstarted threads (ident is None until start()) or a
            # sibling's prune could silently un-track this flood
            self._flood_threads = [
                x for x in self._flood_threads
                if x.ident is None or x.is_alive()
            ]
            self._flood_threads.append(t)
        try:
            t.start()
        except BaseException:
            # never-started floods must not stay tracked (close()'s
            # RuntimeError-tolerant join would retry them to deadline)
            with self._flood_lock:
                if t in self._flood_threads:
                    self._flood_threads.remove(t)
            raise

    def _overlay_targets(self) -> list[int]:
        """This rank's log-degree flood fan-out: skip-ring overlay
        neighbors over the CURRENT live view (:mod:`.overlay`).
        Failed/departed ranks drop out of the member list, so the
        overlay is rebuilt from survivors at shrink by construction —
        no membership protocol, every rank derives the same graph.
        Live peers the old all-pairs flood would have dialed are
        counted in ``tcp_deferred_dials`` (the scaling gate's
        no-silent-fallback evidence)."""
        live = [r for r in range(self.size)
                if r == self.rank or not self.ft_state.is_failed(r)]
        nbrs = overlay.neighbors(self.rank, live)
        skipped = (len(live) - 1) - len(nbrs)
        if skipped > 0:
            spc.record("tcp_deferred_dials", skipped)
        return nbrs

    def _flood_sync(self, cid: int, payload: Any) -> None:
        # overlay fan-out, not all-pairs: receivers relay FRESH facts
        # to THEIR neighbors (_ft_ctrl's gossip-once), so coverage is
        # total while per-event frames stay O(n·log n) universe-wide
        frame = dss.pack(self.rank, 0, cid, 0, payload)
        for r in self._overlay_targets():
            try:
                sock = self._endpoint(r, deadline=1.0)
                self._framed_send(sock, frame)
                spc.record("ft_overlay_hops")
            except (OSError, errors.MpiError):
                pass

    def _ft_flood(self, failed: frozenset) -> None:
        """Propagate suspicion: failure notices to every live rank.
        Entries are ``[rank, cause]`` pairs so a typed classification
        (a device fault) survives the wire; causes that are only LOCAL
        evidence (a detector suspicion, a transport reset) travel as
        second-hand "notice" — the receiver did not observe them, and
        the zero-false-positive gate must keep its meaning.  Receivers
        also accept bare ranks (the pre-pair wire shape)."""
        causes = dict(self.ft_state.failed_with_causes())
        pairs = []
        for r in sorted(int(r) for r in failed):
            cause = causes.get(r, "notice")
            if cause not in ("device", "goodbye"):
                cause = "notice"
            pairs.append([r, cause])
        self._flood(ulfm.FT_NOTICE_CID, pairs, "hb-flood")

    def flood_device_fault(self, fault=None) -> None:
        """Device-plane classification → the same notice flood a
        transport death rides (the ``DeviceLivenessProbe`` on_fault
        hook).  The fault's own ranks are flooded as explicit
        ``device`` pairs — the flood must carry the root cause even if
        a concurrent symptom (this rank's own sm teardown classifying
        as transport death on a peer) wins the mark_failed race
        somewhere (receivers refine circumstantial causes)."""
        if self._ft_dead or self._closed.is_set():
            return
        causes = dict(self.ft_state.failed_with_causes())
        for r in getattr(fault, "failed_ranks", None) or ():
            causes[int(r)] = "device"
        pairs = []
        for r in sorted(causes):
            cause = causes[r]
            if cause not in ("device", "goodbye"):
                cause = "notice"
            pairs.append([int(r), cause])
        self._flood(ulfm.FT_NOTICE_CID, pairs, "device-fault")

    def _mark_transport_death(self, dest: int) -> None:
        """Classify a transport-evidenced death (connection reset /
        refused past backoff / sm consumer stopped) AND flood the
        notice, exactly as the detector floods its suspicions: without
        propagation every rank discovers the corpse independently, and
        a ring observer can false-positive its NEW observed before
        that rank redirects its beats away from the corpse (the
        reconfiguration grace race, observed under scheduler noise)."""
        if self.ft_state.mark_failed(dest, cause="transport") \
                and not self._ft_dead and not self._closed.is_set():
            self._ft_flood(self.ft_state.failed())

    def _agree_announce(self, seq: int, result) -> None:
        """Flood a completed agreement's value into the live peers'
        result registries (the recovery channel of :func:`ulfm.agree`):
        a survivor the dead coordinator never reached adopts the value
        from its registry instead of waiting out a round nobody can
        finish — and a re-elected coordinator gathering from an
        already-departed participant converges the same way.  The value
        is carried verbatim (DSS-packable): a bool for the flag
        AND-reduction, a [pairs, epoch] list for the failed-set
        agreement — coercion here would hand adopters of a failed-set
        result a bare flag they cannot unpack."""
        self._flood(ulfm.FT_AGREE_PUB_CID, [int(seq), result],
                    "agree-pub")

    def _ft_ctrl(self, cid: int, src: int, payload: Any) -> None:
        """Control frames intercepted before the matching engine."""
        if cid == ulfm.FT_HB_CID:
            if self._detector is not None:
                self._detector.transport.on_beat(src)
        elif cid == ulfm.FT_NOTICE_CID:
            # entries are [rank, cause] pairs (typed causes — "device"
            # — survive the wire; see _ft_flood) or bare ranks (the
            # pre-pair shape: second-hand "notice")
            fresh = []
            for entry in payload:
                if isinstance(entry, (list, tuple)):
                    r, cause = int(entry[0]), str(entry[1])
                    if cause == "goodbye":
                        if self.ft_state.mark_departed(r):
                            fresh.append([r, cause])
                    elif self.ft_state.mark_failed(r, cause=cause):
                        fresh.append([r, cause])
                    elif cause == "device":
                        # the typed classification lost the race to a
                        # downstream symptom (the wedged rank's sm
                        # teardown classifies as transport death on
                        # peers mid-send): adopt the root cause
                        self.ft_state.refine_cause(r, cause)
                else:
                    r = int(entry)
                    if self.ft_state.mark_failed(r, cause="notice"):
                        fresh.append([r, "notice"])
            if fresh and not self._ft_dead and not self._closed.is_set():
                # gossip-once relay onto OUR overlay neighbors: the
                # origin only dialed ITS log-degree fan-out, so a
                # non-neighbor survivor learns through relays; mark_*
                # returning False for known facts bounds each rank to
                # one relay per fact and terminates the flood
                self._flood(ulfm.FT_NOTICE_CID, fresh, "notice-gossip")
        elif cid == ulfm.FT_REVOKE_CID:
            if self.ft_state.revoke(int(payload)) \
                    and not self._ft_dead and not self._closed.is_set():
                # newly-learned revocation: relay (overlay gossip)
                self._flood(ulfm.FT_REVOKE_CID, int(payload),
                            "revoke-gossip")
        elif cid == ulfm.FT_AGREE_PUB_CID:
            seq, result = payload
            # verbatim: agreement values are typed by their protocol
            # (bool for agree(), [pairs, epoch] for agree_failed_set())
            if self.ft_state.record_agreement(int(seq), result) \
                    and not self._ft_dead and not self._closed.is_set():
                # newly-adopted announce: relay so survivors outside
                # the coordinator's overlay fan-out converge too
                self._flood(ulfm.FT_AGREE_PUB_CID,
                            [int(seq), result], "agree-gossip")
        elif cid == ulfm.FT_DVM_CID:
            # authoritative fault event from the runtime daemon (zprted
            # waitpid-watched the corpse exit, or a parent daemon saw a
            # whole subtree's link drop): OS truth, not suspicion —
            # classify immediately, before any heartbeat window
            # expires.  The daemon tree floods every survivor itself
            # (each daemon notifies the ranks IT hosts), so no onward
            # relay is needed.  A third entry value names the cause
            # ("daemon-tree" = the rank died WITH its host daemon).
            fresh = 0
            for entry in payload:
                if isinstance(entry, (list, tuple)):
                    r = int(entry[0])
                    cause = str(entry[2]) if len(entry) > 2 \
                        else "daemon"
                else:
                    r, cause = int(entry), "daemon"
                if self.ft_state.mark_failed(r, cause=cause):
                    fresh += 1
            if fresh:
                spc.record("dvm_fault_events", fresh)
        elif cid == ulfm.FT_BYE_CID:
            # relay newly-learned departures onward (gossip-once): the
            # departing rank goodbyes only its CONNECTED peers, so a
            # survivor it never dialed would otherwise re-learn the rank
            # the hard way — ring reconfiguration adopts it as observed
            # successor, sees no beats, and scores a detector false
            # positive for a clean exit.  mark_departed returns False
            # for anything already known, so each rank relays a given
            # departure at most once and the flood terminates.
            fresh = [int(r) for r in payload
                     if self.ft_state.mark_departed(int(r))]
            if fresh and not self._ft_dead and not self._closed.is_set():
                self._flood(ulfm.FT_BYE_CID, fresh, "bye-gossip")

    def _announce_join(self, timeout: float) -> None:
        """Re-modex for a respawned rank (the JOIN half of the recovery
        pipeline): dial every presumed-live survivor from the inherited
        address book, announce the fresh endpoint, and adopt the
        survivors' collective/agreement sequence counters and crash
        epoch from their JOIN_ACKs — so the replacement's next full-size
        collective tags identically to the survivors' and a post-rejoin
        shrink can never reuse an earlier generation's cid window.  The
        pipeline contract is that respawn happens at a survivor barrier
        (post-rollback), so the ack'd counters are stable."""
        frame = dss.pack(self.rank, 0, ulfm.FT_JOIN_CID, 0,
                         ["join", self.rank, list(self.address)])
        reached = 0
        for r in range(self.size):
            if r == self.rank or r in self._rejoin_ranks:
                # a fellow replacement of the SAME recovery window needs
                # no JOIN from us: both sides already hold each other's
                # FRESH generation-tagged cards from the store, neither
                # has the other marked failed, and dialing a sibling
                # still mid-construction would race its wiring
                continue
            if self.ft_state.is_failed(r):
                # a known-dead or elastic-absent slot: nothing to
                # announce to (its placeholder address dials nowhere)
                continue
            try:
                sock = self._endpoint(r, deadline=min(2.0, timeout))
                self._framed_send(sock, frame)
                reached += 1
            except (OSError, errors.MpiError):
                continue  # a peer that is itself gone: its own recovery
        if reached == 0:
            raise errors.InternalError(
                "rejoin: no survivor reachable for the JOIN re-modex"
            )
        deadline = time.monotonic() + timeout
        with self._join_cv:
            while not self._join_acks:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise errors.InternalError(
                        "rejoin: no JOIN_ACK from any survivor"
                    )
                self._join_cv.wait(min(left, 0.05))
            acks = list(self._join_acks.values())
        self._coll_seq = max(a[0] for a in acks)
        self._agree_seq = max(a[1] for a in acks)
        self.ft_state.raise_epoch(max(a[2] for a in acks))

    def _ft_join(self, conn: socket.socket, src: int, payload: Any) -> None:
        """JOIN/re-modex control family (runs on the drain thread, which
        is the one place the carrying connection is in hand).  "join": a
        respawned rank announces its fresh endpoint — swap it in as the
        canonical connection (the pre-crash cached socket is a severed
        corpse), update the address book, clear the failure record so
        classification stops typing the rank dead, give the detector a
        fresh beat window, and ack with our counters.  "ack": the
        survivor's reply, collected by _announce_join."""
        kind = payload[0]
        if kind == "join":
            if getattr(self, "address_book", None) is None:
                # a JOIN landing while THIS endpoint is still wiring up
                # (possible only from another mid-recovery incarnation):
                # nothing to swap yet — our book comes generation-fresh
                # from the store, and the joiner's lazy connects still
                # reach us through the listener
                return
            jrank = int(payload[1])
            addr = tuple(payload[2][:2])
            with self._conn_lock:
                stale = self._conns.get(jrank)
                self._conns[jrank] = conn
            if stale is not None and stale is not conn:
                # the severed pre-crash socket: its drain already exited
                # on the RST; EOF-then-close per the fd-reuse contract
                try:
                    stale.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    stale.close()
                except OSError:
                    pass
            self.address_book[jrank] = addr
            cards = getattr(self, "_peer_cards", None)
            if cards is not None and jrank < len(cards):
                # scrub the dead incarnation's sm card: the respawned
                # rank rides TCP (cohort contract) and must not count
                # as a silent sm fallback either
                cards[jrank] = list(addr)
            with self._sm_lock:
                self._sm_senders[jrank] = None
                self._sm_declined.discard(jrank)
            # membership change: the joiner's locality card was just
            # scrubbed, so the next hierarchical collective must
            # re-derive the groups (the rejoiner is a singleton now)
            from ..coll import han as han_mod

            han_mod.invalidate(self)
            if self._detector is not None:
                self._detector.transport.grace(jrank)
            self.ft_state.restore(jrank)
            ack = ["ack", self.rank, int(getattr(self, "_coll_seq", 0)),
                   int(getattr(self, "_agree_seq", 0)),
                   int(self.ft_state.crash_epoch())]
            try:
                self._framed_send(conn, dss.pack(
                    self.rank, 0, ulfm.FT_JOIN_CID, 0, ack))
            except OSError:
                pass  # the joiner died again: its next respawn's business
        elif kind == "ack":
            with self._join_cv:
                self._join_acks[int(payload[1])] = (
                    int(payload[2]), int(payload[3]), int(payload[4]))
                self._join_cv.notify_all()

    def revoke(self, cid: int) -> None:
        """MPIX_Comm_revoke on the wire: poison locally, flood the
        notice so every live rank's pending and future operations on
        this cid raise ``Revoked``.  Local state is poisoned before the
        flood thread starts, so the revoking rank's own operations fail
        fast and the caller's RECOVERY path never stalls behind the
        flood's connect deadlines."""
        state = self.ft_state
        if state is None:
            raise errors.UnsupportedError(
                "revoke needs fault tolerance enabled (ft=True)"
            )
        state.revoke(cid)
        self._flood(ulfm.FT_REVOKE_CID, int(cid), "revoke-flood")

    def sever(self) -> None:
        """Simulate process death (the fault-injection hook): heartbeats
        stop and every socket is torn down abruptly — no quiescence, no
        goodbye — so peers see connection reset exactly like a crash."""
        self._ft_dead = True
        if self._metrics_pub is not None:
            # a crash publishes nothing more — no final flush (a clean
            # final snapshot from a corpse would lie to the fleet); the
            # thread still dies with the proc (the publisher leak gate)
            self._metrics_pub.abort()
            self._metrics_pub = None
        if self._detector is not None:
            self._detector.stop(join_timeout=0.0)
        self._closed.set()
        # a crash abandons its in-flight deferred sends and parked
        # rendezvous descriptors: waiters unblock ERRORED (typed) and
        # the hygiene gate sees no incomplete request / orphaned park
        self._abandon_inflight("proc severed (simulated crash) with "
                               "sends in flight")
        # a crash abandons its pushes: mark the pool closed so idle
        # workers exit (the hygiene gate counts worker threads)
        self._push_pool.close(0.0)
        if self._sm_seg is not None:
            # consumption stops (the crash contract) but the segment
            # FILE survives — a real crash cleans nothing up; the final
            # harness close()/launcher sweep owns the unlink
            self._sm_seg.sever()
        # the channel engine dies with the proc (a crash reads nothing
        # more); stopping it before the RST closes below means no
        # reader is parked on an fd about to be freed
        if self._chan_engine is not None:
            self._chan_engine.close(1.0)
        try:
            self._listener.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns.values()) + self._dup_conns
            self._conns.clear()
            self._dup_conns = []
        for sock in conns:
            try:
                # RST on close (SO_LINGER 0): peers must observe a reset,
                # not an orderly shutdown — this is a crash, not a close
                sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    struct.pack("ii", 1, 0),
                )
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def mute(self) -> None:
        """Simulate a hang/partition: heartbeats stop, sockets stay up —
        only the failure detector can discover this death."""
        self._ft_dead = True

    def boot_token_of(self, rank: int) -> str | None:
        """Locality identity of ``rank`` as the modex advertised it (the
        boot half of the ``(boot_id, segment)`` card ``pt2pt/sm.py``
        publishes): equal tokens = provably the same host.  None =
        unknown (sm=0 peers, C ranks, rejoiners — their pyshm card was
        scrubbed at JOIN), which the han topology layer groups as its
        own singleton locality.  Own rank reads its OWN relayed card,
        so every rank derives the identical group structure."""
        cards = getattr(self, "_peer_cards", None)
        if cards is None or not 0 <= rank < len(cards):
            return None
        card = sm_mod.parse_card(cards[rank])
        return card[0] if card is not None else None

    def numa_token_of(self, rank: int):
        """NUMA-domain identity of ``rank`` as the modex advertised it
        (the ``pynuma:`` card item): a token string, None when absent
        (old/foreign cards — the host degrades to one domain), or the
        :data:`~zhpe_ompi_tpu.pt2pt.sm.NUMA_MALFORMED` sentinel.  Own
        rank reads its OWN relayed card, so every rank derives the
        identical nested structure."""
        cards = getattr(self, "_peer_cards", None)
        if cards is None or not 0 <= rank < len(cards):
            return None
        return sm_mod.parse_numa(cards[rank])

    def resource_stats(self) -> dict:
        """Per-rank live transport resources — the scale-out
        scaling-curve gates read this at n ∈ {8, 32, 128}: every count
        must fit the ``a·log2(n)+b`` bound.  ``sockets`` counts cached
        peer connections (canonical + crossed dups), ``channels`` the
        engine's registered readers (sockets plus inbound-accepted
        conns), ``threads`` the transport-owned reader/push/flood
        threads (ONE engine reader regardless of connection count —
        the thread-per-connection replacement)."""
        with self._conn_lock:
            socks = len(self._conns) + len(self._dup_conns)
        eng = self._chan_engine
        chans = eng.channel_count() if eng is not None else 0
        threads = 1 if eng is not None and not eng.closed else 0
        threads += len(self._push_pool.alive_threads())
        with self._flood_lock:
            threads += sum(
                1 for t in self._flood_threads if t.is_alive())
        return {"sockets": socks, "channels": chans,
                "threads": threads}

    def sm_segment_stats(self) -> dict | None:
        """Demand-mapping introspection of this proc's OWN segment (the
        OSU numa ladder's footprint gate): materialized inbound ring
        sources, the bitmap-derived logical footprint, and the actual
        tmpfs page bytes.  None when the sm plane is off."""
        seg = self._sm_seg
        if seg is None:
            return None
        return {
            "materialized": seg.materialized(),
            "footprint_bytes": seg.footprint_bytes(),
            "physical_bytes": seg.physical_bytes(),
        }

    # -- one-sided plane seam (osc/direct.py) ----------------------------

    def sm_rma_region(self, nbytes: int):
        """Allocate an RMA region (window/symmetric-heap backing) in
        this proc's sm segment namespace; None when the sm plane is
        off — the window then rides the AM path everywhere."""
        if self._sm_seg is None:
            return None
        return self._sm_seg.alloc_rma_region(nbytes)

    def sm_release_region(self, region) -> None:
        if self._sm_seg is not None:
            self._sm_seg.release_rma_region(region)
        else:  # segment already torn down: best-effort unlink
            region.close(unlink=True)

    def sm_direct_to(self, dest: int) -> bool:
        """The one-sided plane's per-peer seam decision: True when the
        PR 4 transport ladder selected the sm ring for `dest` (same
        boot, sm priority, not declined/failed) — the EXACT decision
        the two-sided send seam memoized, so a direction is direct for
        RMA iff its data channel rides the rings.  Rank-to-self is
        direct whenever the sm plane is on (the owner maps its own
        region trivially)."""
        if dest == self.rank:
            return self._sm_seg is not None
        return self._sm_tx(dest) is not None

    # -- wire-up ---------------------------------------------------------

    def _my_card(self) -> list:
        """This rank's modex business card: ``[host, port]`` plus
        capability items — the sm segment advertisement rides here the
        way C ranks advertise their ring capability (extra items are
        relayed verbatim and ignored by consumers that only dial
        sockets)."""
        card = list(self.address)
        if self._sm_seg is not None:
            card.append(self._sm_seg.card(self._sm_boot))
            # NUMA-domain token (the host→domain nesting level): only
            # meaningful next to a locality (pyshm) item — a rank with
            # no provable host is a singleton either way
            card.append(sm_mod.numa_card_item(self._sm_numa))
        return card

    def _modex_pmix(self, timeout: float) -> list[tuple[str, int]]:
        """Business-card exchange through the name-served PMIx store
        (the PRRTE-hosts-the-PMIx-server shape of runtime/pmix.py):
        put our card under ``card:<rank>``, commit, fence the
        namespace, then get every peer's card — get-until-published
        blocking means no rank ever races a slower peer's publish.
        A resident DVM hosts the store across jobs, so this path pays
        no per-job rendezvous infrastructure at all."""
        from ..runtime import pmix as pmix_mod

        client = pmix_mod.PmixClient(self._pmix_addr, timeout=timeout)
        try:
            # elastic jobs fence over the STARTED set only (the
            # namespace size is the initial live count — absent slots
            # would park the barrier forever); their cards are
            # placeholders until a grow's FT_JOIN announces the truth
            live = self._live_ranks
            client.ensure_ns(self._pmix_ns,
                             self.size if live is None else len(live))
            client.put(self._pmix_ns, self.rank, f"card:{self.rank}",
                       self._my_card())
            client.commit(self._pmix_ns, self.rank)
            client.fence(self._pmix_ns, self.rank, timeout)
            book = [
                client.get(self._pmix_ns, f"card:{r}", timeout)
                if live is None or r in live else ["0.0.0.0", 0]
                for r in range(self.size)
            ]
        except errors.MpiError as e:
            return self.call_errhandler(errors.InternalError(
                f"pmix modex via {self._pmix_addr} "
                f"ns={self._pmix_ns!r}: {e}"
            ))
        finally:
            client.close()
        self._peer_cards = [list(a) for a in book]
        return [tuple(a[:2]) for a in book]

    def _pmix_rejoin_book(self, timeout: float) -> list:
        """The respawned rank's half of the name-served rejoin: publish
        OUR fresh card FIRST (so co-replacements blocked on this
        window's generation release), then read the book — survivors'
        cards plain, but ranks respawned in the SAME recovery window
        (``rejoin_ranks``) at ``min_generation=rejoin_gen``: a plain
        get would be satisfied by the corpse's generation-old card and
        both replacements would dial each other's dead addresses with
        nothing ever healing the books (JOIN announces to a dead
        address are skipped, not relayed).  Publish-before-read keeps
        the batch deadlock-free.  The JOIN announce to the survivors
        still rides the FT_JOIN wire family unchanged."""
        from ..runtime import pmix as pmix_mod

        client = pmix_mod.PmixClient(self._pmix_addr, timeout=timeout)
        try:
            client.put(self._pmix_ns, self.rank, f"card:{self.rank}",
                       self._my_card())
            client.commit(self._pmix_ns, self.rank)
            book = []
            for r in range(self.size):
                if self._live_ranks is not None \
                        and r not in self._live_ranks:
                    # an absent elastic slot: no card to wait for (a
                    # retired slot's STALE card must not be dialed)
                    book.append(["0.0.0.0", 0])
                    continue
                min_gen = self._rejoin_gen \
                    if r != self.rank and r in self._rejoin_ranks else 0
                book.append(client.get(self._pmix_ns, f"card:{r}",
                                       timeout, min_generation=min_gen))
        finally:
            client.close()
        return book

    def _modex(self, coordinator: tuple[str, int], timeout: float
               ) -> list[tuple[str, int]]:
        if self.rank == 0 and not self._external_coordinator:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind(coordinator)
            srv.listen(self.size + 4)
            self.coordinator_address = srv.getsockname()
            if self._on_coordinator_bound is not None:
                self._on_coordinator_bound(self.coordinator_address)
            book: list[Any] = [None] * self.size
            book[0] = self._my_card()
            peers = []
            srv.settimeout(timeout)
            for _ in range(self.size - 1):
                conn, _addr = srv.accept()
                [peer_rank, addr] = dss.unpack(_recv_frame(conn))
                book[peer_rank] = addr
                peers.append(conn)
            payload = dss.pack(book)
            for conn in peers:
                _send_frame(conn, payload)
                conn.close()
            srv.close()
            # the RELAYED book keeps every card verbatim (C peers read
            # capability items); the LOCAL book normalizes to
            # (host, port) — the full cards are kept for the sm
            # transport's endpoint selection
            self._peer_cards = [list(a) for a in book]
            return [tuple(a[:2]) for a in book]
        cli = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        cli.settimeout(timeout)
        deadline_err = None
        # backoff bounded by the modex deadline: a slow-starting
        # coordinator is retried patiently but never past `timeout` —
        # distinguishing "not up yet" from "never coming" by the total
        # budget, not a fixed attempt count
        backoff = _Backoff(timeout, self.rank ^ 0x5EED)
        connected = False
        while not backoff.expired():
            try:
                cli.connect(coordinator)
                connected = True
                break
            except OSError as e:
                deadline_err = e
                cli.close()
                backoff.sleep()
                cli = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                cli.settimeout(timeout)
        if not connected:
            # transport failure routes through the errhandler disposition
            # (ompi_errhandler_invoke at the transport boundary,
            # errhandler.h:94-136): FATAL raises JobAbort, RETURN hands
            # the typed error back to the caller
            exc = errors.InternalError(
                f"modex: cannot reach coordinator {coordinator}: "
                f"{deadline_err}"
            )
            # FATAL raises JobAbort, RETURN raises exc; a user handler's
            # return value becomes the API result (the error-recovery
            # contract of core/errhandler.py)
            return self.call_errhandler(exc)
        _send_frame(cli, dss.pack(self.rank, self._my_card()))
        [book] = dss.unpack(_recv_frame(cli))
        cli.close()
        # normalize at the boundary: C ranks' cards may carry extra
        # capability items beyond (host, port); keep the raw cards for
        # the sm transport's endpoint selection
        self._peer_cards = [list(a) for a in book]
        return [tuple(a[:2]) for a in book]

    def _on_accept(self, conn: socket.socket) -> None:
        """Inbound connection off the channel engine's listener: the
        first frame announces the peer — a bare rank for in-group
        peers, ["b", bridge_cid, rank] for a rank of a REMOTE group
        connecting across an intercomm bridge (dpm, namespaced so
        remote rank numbers cannot collide with local ones in the
        connection cache), or ["d"] for a rendezvous bulk-data
        connection — so the channel starts in a HELLO state and
        retargets itself onto the steady-state frame handler."""
        self._chan_engine.add_channel(
            conn, f"hello:{conn.fileno()}", self._on_hello_frame)

    def _on_hello_frame(self, chan, frame) -> None:
        conn = chan.sock
        [hello] = dss.unpack(frame)
        if isinstance(hello, (list, tuple)) and hello[0] == "d":
            # rendezvous bulk-data connection: drain it, but never
            # register it for sends (control and bulk stay separate)
            with self._conn_lock:
                self._dup_conns.append(conn)
            chan.name = f"data:{conn.fileno()}"
        else:
            if isinstance(hello, (list, tuple)):
                key = ("b", hello[1], hello[2])
            else:
                key = hello
            with self._conn_lock:
                self._conns.setdefault(key, conn)
            chan.name = f"peer:{key}"
        chan.on_frame = self._on_wire_frame

    def _on_wire_frame(self, chan, frame) -> None:
        """One framed message off the channel engine — the per-frame
        body of the old per-connection drain loop (same dispatch,
        same log-and-keep-draining posture: a failing matching
        callback must not kill the channel, every later message on
        this connection would silently vanish)."""
        conn = chan.sock
        # unpack_from: array payloads become writable views over the
        # frame's dedicated recv_into buffer — the zero-copy receive
        # half (the frame bytearray stays alive via the views)
        vals = dss.unpack_from(frame)
        src, tag, cid, seq, payload = vals[:5]
        if self.ft_state is not None and cid == ulfm.FT_JOIN_CID:
            # rejoin/re-modex: needs the carrying connection (the
            # joiner's fresh socket becomes the canonical endpoint)
            self._ft_join(conn, src, payload)
            return
        if self.ft_state is not None and cid in (
            ulfm.FT_HB_CID, ulfm.FT_NOTICE_CID, ulfm.FT_REVOKE_CID,
            ulfm.FT_AGREE_PUB_CID, ulfm.FT_BYE_CID, ulfm.FT_DVM_CID,
        ):
            # ULFM control plane: heartbeats / failure notices /
            # revoke floods never enter the matching engine
            self._ft_ctrl(cid, src, payload)
            return
        self._trace_ingest(vals, "tcp")
        env = Envelope(src, tag, cid, seq)
        try:
            with self._incoming_cv:
                self.engine.incoming(env, payload)
                self._incoming_cv.notify_all()
        except Exception as e:  # noqa: BLE001 - log, keep draining
            mca_output.emit(
                _stream,
                "rank %s: matching callback failed for (src=%s tag=%s "
                "cid=%s): %s: %s", self.rank, src, tag, cid,
                type(e).__name__, e,
            )

    def _endpoint(self, dest: int,
                  deadline: float | None = None) -> socket.socket:
        with self._conn_lock:
            sock = self._conns.get(dest)
        if sock is not None:
            return sock
        if self.ft_state is not None and self.ft_state.is_failed(dest):
            raise errors.ProcFailed(
                f"rank {dest} is known failed",
                failed_ranks=self.ft_state.failed(),
            )
        # lazy connection establishment (btl_tcp_endpoint shape) with
        # exponential backoff + jitter bounded by a total deadline: a
        # peer still wiring up is retried, not misclassified as dead.
        # Cards may carry extra capability items beyond (host, port) —
        # C ranks advertise their shared-memory transport there — so
        # the connect address is always the 2-prefix.
        addr = tuple(self.address_book[dest][:2])
        budget = self._timeout if deadline is None else deadline
        backoff = _Backoff(budget, (self.rank << 16) ^ dest)
        sock = None
        while True:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            sock.settimeout(max(0.05, min(self._timeout, budget)))
            try:
                sock.connect(addr)
                break
            except OSError as e:
                try:
                    sock.close()
                except OSError:
                    pass
                state = self.ft_state
                if state is not None and state.is_failed(dest):
                    raise errors.ProcFailed(
                        f"rank {dest} failed while connecting",
                        failed_ranks=state.failed(),
                    ) from e
                if state is None and isinstance(
                    e, (ConnectionRefusedError, ConnectionResetError)
                ):
                    # non-ft: the peer advertised this port through the
                    # modex, so its listener WAS bound — refused now
                    # means it is gone, and without ft there is no
                    # rejoin path that could re-bind it.  Fail fast
                    # (the seed behavior) instead of burning the whole
                    # backoff budget on a corpse.
                    raise
                if backoff.expired(lookahead=backoff.delay):
                    if state is not None and isinstance(
                        e, (ConnectionRefusedError, ConnectionResetError)
                    ):
                        # refused past the backoff budget: the peer's
                        # listener is gone — that is death, not a stall
                        self._mark_transport_death(dest)
                        raise errors.ProcFailed(
                            f"rank {dest} unreachable "
                            f"(connection refused/reset): {e}",
                            failed_ranks=state.failed(),
                        ) from e
                    raise
                backoff.sleep()
        # the connect BUDGET must not become the socket's steady-state
        # timeout: a 0.2s heartbeat budget would bound every later send
        # on this cached socket (and starve its peer-side drain)
        sock.settimeout(self._timeout)
        _send_frame(sock, dss.pack(self.rank))
        # every fresh outbound dial is a LAZY connect (modex handed out
        # cards, not sockets): the scaling gate reads this counter to
        # prove wire-up never silently reverts to eager all-pairs
        spc.record("tcp_lazy_connects")
        with self._conn_lock:
            existing = self._conns.get(dest)
            if existing is not None:
                # simultaneous connect: the peer may have ALREADY
                # registered our socket as ITS canonical endpoint (its
                # accept saw our hello) — closing it here would RST the
                # peer's first frames after its sendall returned, a
                # silent rare message loss.  Keep both crossed
                # connections; each side sends only on its registered
                # one, so per-source FIFO is preserved.
                self._dup_conns.append(sock)
                self._chan_engine.add_channel(
                    sock, f"peer:{dest}-x", self._on_wire_frame)
                return existing
            self._conns[dest] = sock
        self._chan_engine.add_channel(
            sock, f"peer:{dest}", self._on_wire_frame)
        return sock

    def bridge_endpoint(self, cid: int, dest: int,
                        addr: tuple[str, int]) -> socket.socket:
        """Lazy connection to rank `dest` of a REMOTE group across an
        intercomm bridge (dpm) — cached under the bridge cid so remote
        rank numbering stays disjoint from the in-group book."""
        key = ("b", cid, dest)
        with self._conn_lock:
            sock = self._conns.get(key)
        if sock is not None:
            return sock
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.settimeout(self._timeout)
        sock.connect(tuple(addr))
        _send_frame(sock, dss.pack(["b", cid, self.rank]))
        spc.record("tcp_lazy_connects")
        with self._conn_lock:
            existing = self._conns.get(key)
            if existing is not None:
                # crossed-connection rule: never close a socket whose
                # hello the peer may have registered (see _endpoint)
                self._dup_conns.append(sock)
                self._chan_engine.add_channel(
                    sock, f"bridge:{cid}:{dest}-x", self._on_wire_frame)
                return existing
            self._conns[key] = sock
        self._chan_engine.add_channel(
            sock, f"bridge:{cid}:{dest}", self._on_wire_frame)
        return sock

    def bridge_send(self, obj: Any, cid: int, dest: int,
                    addr: tuple[str, int], tag: int = 0) -> None:
        """Send to a remote-group rank across a bridge; frames carry the
        bridge cid so matching stays isolated from in-group traffic."""
        seq = next(self._seq)
        header, oob = dss.pack_frames(
            self.rank, tag, cid, seq, obj,
            oob_min=int(mca_var.get("tcp_zero_copy_min", 0)),
        )
        sock = self.bridge_endpoint(cid, dest, addr)
        self._framed_send(sock, [header, *oob])
        if oob:
            spc.record("tcp_zero_copy_sends", 1)
            spc.record("tcp_copy_bytes_avoided",
                       sum(v.nbytes for v in oob))

    # -- MPI surface (RankContext-compatible) ----------------------------

    def send(self, obj: Any, dest: int, tag: int = 0, cid: int = 0,
             poll: bool = False) -> None:
        """Length-framed send: eager below ``tcp_eager_limit``, RTS/CTS
        rendezvous above it (ob1's protocol split on the wire — an
        unmatched multi-GB send must park at the SENDER, not in the
        receiver's unexpected queue).  The rendezvous payload is
        serialized at send time, so the MPI buffer-reuse contract holds
        the moment this returns.

        ``poll=True`` marks a framework-internal send (e.g. an agreement
        round): typed failures raise directly, bypassing the errhandler
        disposition, so fault-tolerant protocols can observe and recover
        from peer death regardless of the user's disposition."""
        if not 0 <= dest < self.size:
            raise errors.RankError(f"rank {dest} out of range")
        if tag < 0:
            raise errors.TagError(f"negative tag {tag}")
        if flightrec.active and not poll:
            # the postmortem ring: user-facing traffic only (poll=True
            # protocol sends would drown the window in heartbeat noise)
            flightrec.record(flightrec.SEND, rank=self.rank, dest=dest,
                             tag=tag, cid=cid)
        state = self.ft_state
        if state is not None and state.is_revoked(cid):
            # before ANY delivery path, the loopback fast path included:
            # a revoked cid poisons sends to self like any other
            exc: errors.MpiError = errors.Revoked(
                f"send on revoked cid={cid}", cid=cid
            )
            if poll:
                raise exc
            return self.call_errhandler(exc)
        seq = next(self._seq)
        # tracing plane (armed only): the send span opens here and its
        # wire context rides the frame header on every transport below;
        # an error path that never ends the span leaves it unrecorded —
        # the missing span IS the postmortem signal
        tspan = tctx = None
        if ztrace.active and not poll:
            tspan = ztrace.begin(ztrace.SEND, self.rank, dest=dest,
                                 tag=tag, cid=cid, seq=seq)
            tctx = ztrace.wire_context(tspan.sid, seq)
            if tctx is None:
                tspan = None  # a disarm raced begin(): send untraced
        if dest == self.rank:
            # loopback shortcut (btl/self): ONE defensive copy with the
            # DSS type mapping instead of the full serialize/deserialize
            # round trip — the receiver still sees the pre-mutation
            # value if the sender reuses its buffer immediately
            nbytes = _payload_size(obj)
            try:
                payload = _loopback_copy(obj)
                spc.record("tcp_loopback_fast_deliveries", 1)
                spc.record("tcp_copy_bytes_avoided", nbytes)
            except _LoopbackFallback:
                frame = dss.pack(self.rank, tag, cid, seq, obj)
                payload = dss.unpack(frame)[4]
            env = Envelope(self.rank, tag, cid, seq)
            with self._incoming_cv:
                self.engine.incoming(env, payload)
                self._incoming_cv.notify_all()
            if tspan is not None:
                # no wire: the deliver span parents directly
                ztrace.instant(ztrace.DELIVER, self.rank,
                               parent=tspan.sid, trace=tctx[0],
                               src=self.rank, tag=tag, cid=cid, seq=seq,
                               transport="self")
                tspan.end(transport="self")
            return
        nbytes = _payload_size(obj)
        # deferred frames queued toward this peer drain FIRST: blocking
        # sends write the socket/ring inline, and per-source FIFO must
        # hold across both send paths (isend then send may not reorder)
        try:
            self._send_fence(dest)
        except errors.InternalError as exc:
            if poll:
                raise
            return self.call_errhandler(exc)
        # per-peer transport dispatch (the btl selection seam): the sm
        # ring wins for same-boot peers by priority; everything below —
        # eager/rendezvous split, SPC accounting, FT classification —
        # is the TCP path the pair degrades to
        smtx = self._sm_tx(dest)
        if smtx is not None:
            try:
                spins0 = sm_mod.thread_full_spins() \
                    if tspan is not None else 0
                self._sm_send(smtx, obj, dest, tag, cid, seq, nbytes,
                              tctx=tctx)
                if tspan is not None:
                    # bp: the span's duration includes ring-full
                    # backpressure — the critical-path report's
                    # ring-backpressure classification keys on this.
                    # THREAD-local spins: the global counter would
                    # blame another sender's full ring on this span
                    tspan.end(transport="sm",
                              bp=sm_mod.thread_full_spins() > spins0)
                return
            except errors.ProcFailed as exc:
                if poll:
                    raise
                return self.call_errhandler(exc)
            except sm_mod.ConsumerStopped as e:
                # the ring's owner stopped consuming: on an ft proc that
                # IS peer death — the sm twin of connection reset (the
                # detector/BYE may simply not have landed yet); classify
                # instead of surfacing a bare transport error
                if state is None:
                    if poll:
                        raise
                    return self.call_errhandler(e)
                self._mark_transport_death(dest)
                exc = errors.ProcFailed(
                    f"rank {dest} failed (sm ring consumer stopped): "
                    f"{e}", failed_ranks=state.failed(),
                )
                if poll:
                    raise exc from e
                return self.call_errhandler(exc)
            except errors.InternalError as exc:
                # wedged/closed ring: a transport failure, not a crash —
                # same disposition routing as a TCP stall would get
                if poll:
                    raise
                return self.call_errhandler(exc)
        if dest in self._sm_declined:
            # the peer advertised an sm endpoint we could not ride
            # (boot mismatch, unmappable segment): the degradation is
            # visible, not silent — the OSU ladder gate asserts zero
            spc.record("sm_fallback_tcp_sends", 1)
        limit = int(mca_var.get("tcp_eager_limit", 1 << 20))
        try:
            if nbytes > limit:
                self._send_rndv(obj, dest, tag, cid, seq, nbytes,
                                tctx=tctx,
                                parent=tspan.sid if tspan is not None
                                else None)
                if tspan is not None:
                    tspan.end(transport="rndv")
                return
            # eager zero-copy: array/bytes payloads leave as out-of-band
            # memoryview segments of the CALLER's buffers, gathered by
            # sendmsg — the blocking send completes only after the
            # kernel has the bytes, so buffer reuse stays safe
            header, oob = dss.pack_frames(
                *self._frame_objs(tag, cid, seq, obj, tctx),
                oob_min=int(mca_var.get("tcp_zero_copy_min", 0)),
            )
            sock = self._endpoint(dest)
            self._framed_send(sock, [header, *oob])
            if oob:
                spc.record("tcp_zero_copy_sends", 1)
                spc.record("tcp_copy_bytes_avoided",
                           sum(v.nbytes for v in oob))
            if tspan is not None:
                tspan.end(transport="tcp")
        except errors.ProcFailed as exc:
            # peer death classified by the endpoint layer: route through
            # the attached disposition (FATAL aborts, RETURN raises typed)
            if poll:
                raise
            return self.call_errhandler(exc)
        except OSError as e:
            if state is None or not isinstance(
                e, (ConnectionRefusedError, ConnectionResetError,
                    BrokenPipeError)
            ):
                # a stalled send (timeout on a live but slow peer) is NOT
                # death — only reset/refused/pipe is; the endpoint layer
                # already re-raised non-death errors raw, honor that here
                raise
            self._mark_transport_death(dest)
            exc = errors.ProcFailed(
                f"send to rank {dest} failed: {e}",
                failed_ranks=state.failed(),
            )
            if poll:
                raise exc from e
            return self.call_errhandler(exc)

    def _push_rndv(self, rndv_id: int, dest: int, req=None) -> None:
        """CTS-released bulk push over a dedicated per-transfer data
        connection (hello ["d"]).  Runs on a push-pool worker over its
        OWN socket: the drain must keep reading while this send blocks
        (drain stuck in a writer = bidirectional deadlock), and the bulk
        write must not hold the control socket's framing lock — a tiny
        CTS queued behind a multi-MB sendall re-creates the same
        deadlock one level up; ob1 separates its channels for the same
        reason.  ``req`` is the isend path's SendRequest: the push's
        outcome completes it (the blocking path passes None — its
        buffer-reuse contract was settled by the park copy)."""
        data_sock = None
        err: BaseException | None = None
        sent = False
        tparent = None
        t0_ns = 0
        if ztrace.active:
            with self._rndv_lock:
                tparent = self._rndv_trace.get(rndv_id)
            t0_ns = time.monotonic_ns()
        try:
            with self._rndv_lock:
                frame_segs = self._pending_rndv.get(rndv_id)
                if frame_segs is not None and req is not None \
                        and not req.done:
                    # push in flight: owned ATOMICALLY with the frame
                    # read, under the same lock the failure listener
                    # holds — no window where both sides claim it
                    req._owned = True
            if frame_segs is None or (req is not None and req.done):
                # poisoned/abandoned while parked (revoke, peer death,
                # sever): the poisoner owns the request's completion
                return
            data_sock = socket.socket(
                socket.AF_INET, socket.SOCK_STREAM
            )
            data_sock.settimeout(self._timeout)
            data_sock.connect(tuple(self.address_book[dest][:2]))
            _send_frame(data_sock, dss.pack(["d"]))
            _send_frame(data_sock, frame_segs)
            sent = True
        except BaseException as e:  # noqa: BLE001 - typed at the req
            # ANY escape (not just OSError) must complete the request:
            # the finally below drops the park entries, so a request
            # left incomplete here could never be completed by the
            # failure listener or the close-time abandon sweep again
            err = e
            mca_output.emit(
                _stream,
                "rank %s: rendezvous data push to %s failed: %s",
                self.rank, dest, e,
            )
        finally:
            if data_sock is not None:
                try:
                    data_sock.close()
                except OSError:
                    pass
            # always release the entry: close()'s quiesce loop would
            # otherwise spin its full timeout on a dead transfer
            with self._rndv_lock:
                self._pending_rndv.pop(rndv_id, None)
                self._rndv_meta.pop(rndv_id, None)
                self._rndv_trace.pop(rndv_id, None)
            if sent and tparent is not None and ztrace.active:
                # the CTS-released bulk leg, duration included —
                # parented on the originating send span
                ztrace.record_span(ztrace.PUSH, self.rank, t0_ns,
                                   time.monotonic_ns(), parent=tparent,
                                   dest=dest)
            if req is not None:
                if sent:
                    req.complete()
                elif err is not None:
                    req.complete_error(self._deferred_exc(err, dest))

    def _park_rndv(self, obj: Any, dest: int, seq: int,
                   req=None, tctx=None, parent=None) -> tuple[int, list]:
        """Serialize and park one rendezvous transfer; returns
        ``(rndv_id, oob_segments)``.  The blocking path (``req=None``)
        parks one defensive ``bytes()`` copy per payload block — its
        buffer-reuse contract holds the moment send() returns; the
        isend path parks the DESCRIPTOR (the caller's own memoryview
        segments, zero copies) because its contract is deferred to
        request completion.  While tracing is armed the DATA frame
        carries the send span's wire context (the receiver's deliver
        span parents on it) and ``parent`` seeds the push leg's span."""
        rndv_id = next(self._rndv_ids)
        header, oob = dss.pack_frames(
            *self._frame_objs(rndv_id, _RNDV_DATA_CID, seq, obj, tctx),
            oob_min=int(mca_var.get("tcp_zero_copy_min", 0)),
        )
        if parent is not None:
            with self._rndv_lock:
                self._rndv_trace[rndv_id] = int(parent)
        if req is None:
            segments = [header] + [bytes(v) for v in oob]
            spc.record("tcp_rndv_park_copy_bytes",
                       sum(v.nbytes for v in oob))
        else:
            segments = [header, *oob]
            req._pinned = segments
            spc.record("rndv_park_bytes_avoided",
                       sum(v.nbytes for v in oob))
        with self._rndv_lock:
            self._pending_rndv[rndv_id] = segments
            self._rndv_meta[rndv_id] = (dest, req)
        spc.record("tcp_rndv_sends", 1)
        if oob:
            spc.record("tcp_zero_copy_sends", 1)
            spc.record("tcp_copy_bytes_avoided",
                       sum(v.nbytes for v in oob))

        def on_cts(_env, _payload):
            self._push_pool.submit(
                lambda: self._push_rndv(rndv_id, dest, req))

        with self._incoming_cv:
            self.engine.post_recv(dest, rndv_id, _RNDV_CTS_CID, on_cts)
        return rndv_id, oob

    def _send_rndv(self, obj: Any, dest: int, tag: int, cid: int,
                   seq: int, nbytes: int, tctx=None,
                   parent=None) -> None:
        """RTS/CTS rendezvous: serialize the payload now (buffer-reuse
        contract), park the data frame locally, announce with a small RTS
        carrying the envelope; the receiver's CTS — handled in the drain
        thread — releases the data on a dedicated (rndv_id, cid) channel."""
        rndv_id, _oob = self._park_rndv(obj, dest, seq, tctx=tctx,
                                        parent=parent)
        rts = dss.pack(
            *self._frame_objs(
                tag, cid, seq, (_RTS_MARK, self.rank, rndv_id, nbytes),
                tctx),
        )
        sock = self._endpoint(dest)
        self._framed_send(sock, rts)
        if parent is not None and ztrace.active:
            # the announce leg, parented on the send span
            ztrace.instant(ztrace.RTS, self.rank, parent=parent,
                           dest=dest, tag=tag, cid=cid, seq=seq,
                           nbytes=nbytes)

    def _resolve_rndv(self, env: Envelope, payload: Any, deliver) -> bool:
        """If `payload` is an RTS marker, pull the real payload over
        (post the data recv, then CTS) and call ``deliver(env, data)``
        when it lands; returns True when a rendezvous was initiated."""
        if not (isinstance(payload, tuple) and len(payload) == 4
                and payload[0] == _RTS_MARK):
            return False
        _, sender, rndv_id, _nbytes = payload

        def on_data(_env2, data):
            deliver(env, data)

        # may be called from a drain thread (engine entry points are
        # internally locked; _incoming_cv is NOT re-acquired here because
        # matching callbacks already run under it)
        self.engine.post_recv(sender, rndv_id, _RNDV_DATA_CID, on_data)
        cts = dss.pack(self.rank, rndv_id, _RNDV_CTS_CID, next(self._seq),
                       b"")
        sock = self._endpoint(sender)
        self._framed_send(sock, cts)
        return True

    # -- deferred-contract nonblocking send engine -----------------------

    def _channel(self, dest: int) -> _OutChannel:
        ch = self._out_channels.get(dest)
        if ch is None:
            with self._out_lock:
                ch = self._out_channels.setdefault(dest, _OutChannel())
        return ch

    def _enqueue_deferred(self, dest: int, req, work,
                          finish: bool = True) -> None:
        """Queue one unit of deferred send work for ``dest`` and make
        sure exactly one worker owns the channel's drain."""
        ch = self._channel(dest)
        with ch.lock:
            ch.queue.append((work, req, finish))
            start = not ch.draining
            if start:
                ch.draining = True
        if start:
            self._push_pool.submit(
                lambda: self._drain_channel(ch, dest))

    def _drain_channel(self, ch: _OutChannel, dest: int) -> None:
        """Push-pool worker body: drain one destination's deferred
        frames strictly in order; a failing item completes its request
        ERRORED (typed) and the drain keeps going — later frames to a
        dead peer fail fast on their own, and frames to a live peer
        behind a transient error still deliver.

        Fair-share: the drain owns its worker for at most
        ``_PUSH_RR_QUANTUM`` items while other channels queue on the
        pool — then it re-submits itself to the BACK of the pool queue
        (round-robin across destinations), so one peer's bulk
        rendezvous stream cannot starve another tenant's.  ``draining``
        stays True across the rotation: the single-owner invariant (and
        the per-destination FIFO it guards) holds."""
        done = 0
        while True:
            rotate = False
            with ch.lock:
                if not ch.queue:
                    ch.draining = False
                    return
                if done >= _PUSH_RR_QUANTUM \
                        and self._push_pool.backlog() > 0:
                    rotate = True
                else:
                    work, req, finish = ch.queue.popleft()
                    if req is not None:
                        # ownership set ATOMICALLY with the pop: a
                        # failure classifier either sees the item still
                        # queued (and errors it) or sees it owned —
                        # never a window where a delivered send gets
                        # poisoned (observed: a peer recv'd the frame,
                        # finished, and its goodbye beat the worker to
                        # the completion)
                        req._owned = True
            if rotate:
                spc.record("tcp_push_rr_rotations")
                self._push_pool.submit(
                    lambda: self._drain_channel(ch, dest))
                return
            done += 1
            if req is not None and req.done:
                continue  # poisoned while parked (revoke/death/abandon)
            try:
                work()
            except BaseException as e:  # noqa: BLE001 - typed at the req
                if req is not None:
                    req.complete_error(self._deferred_exc(e, dest))
                    # a failed RTS leaves its rendezvous data parked
                    # with a TERMINAL request: nothing will ever push
                    # or poison it again (_fail_inflight skipped it as
                    # owned during this very send, and the waiter's
                    # poison tick stops with the request) — release it
                    # here or it pins the caller's buffers until
                    # close()'s sweep
                    self._release_rndv_for(req)
                continue
            if finish and req is not None:
                req.complete()
            elif req is not None:
                # RTS sent, data still parked awaiting the CTS: the
                # park/poison machinery owns the request again (a peer
                # that departs before its CTS must error it typed)
                req._owned = False

    def _release_rndv_for(self, req) -> None:
        """Drop parked rendezvous state pinned for ``req``: once the
        request is terminal (its RTS failed on the engine), the park
        can never be pushed — a late CTS for the id is already a
        no-op in the CTS handler, and the conftest orphan gate would
        otherwise only be saved by close()'s known-failed re-sweep."""
        with self._rndv_lock:
            dead = [rid for rid, (_, r) in self._rndv_meta.items()
                    if r is req]
            for rid in dead:
                self._pending_rndv.pop(rid, None)
                self._rndv_meta.pop(rid, None)
                self._rndv_trace.pop(rid, None)

    def _deferred_exc(self, e: BaseException, dest: int):
        """Typed completion error for a deferred send that failed on
        the progress engine — the same classification the blocking
        send path applies, observed at wait() instead of at the call."""
        state = self.ft_state
        if isinstance(e, sm_mod.ConsumerStopped) and state is not None:
            self._mark_transport_death(dest)
            return errors.ProcFailed(
                f"rank {dest} failed (sm ring consumer stopped): {e}",
                failed_ranks=state.failed(),
            )
        if isinstance(e, errors.MpiError):
            return e
        if isinstance(e, OSError):
            if state is not None and isinstance(
                e, (ConnectionRefusedError, ConnectionResetError,
                    BrokenPipeError)
            ):
                self._mark_transport_death(dest)
                return errors.ProcFailed(
                    f"deferred send to rank {dest} failed: {e}",
                    failed_ranks=state.failed(),
                )
            return errors.InternalError(
                f"deferred send to rank {dest} failed: {e}")
        return errors.InternalError(
            f"deferred send to rank {dest} failed: "
            f"{type(e).__name__}: {e}")

    def _send_fence(self, dest: int) -> None:
        """Order a direct (caller-thread) send behind every deferred
        frame already queued toward ``dest``: blocking sends write the
        socket/ring inline, so an in-flight isend to the same peer must
        drain first or per-source FIFO breaks across the two send
        paths.  No channel (the common all-blocking case) costs one
        dict probe."""
        ch = self._out_channels.get(dest)
        if ch is None or not ch.busy():
            return
        deadline = time.monotonic() + self._timeout
        # bounded backoff, not a sub-ms spin: the push-pool worker
        # draining this channel needs the very quanta a hot poll would
        # steal on a 1-CPU host (the PR 6 finding, ZL003) — first waits
        # stay tight so an almost-drained channel costs ~nothing
        delay = 0.0002
        while ch.busy():
            if time.monotonic() > deadline:
                raise errors.InternalError(
                    f"deferred-send queue to rank {dest} failed to "
                    "drain within the stall timeout")
            time.sleep(delay)
            delay = min(delay * 2, 0.005)

    def _arm_isend_poison(self, req, dest: int, cid: int,
                          rndv_id: int | None = None) -> None:
        """Weak-progress poisoning for a parked isend: a revoke flood
        (via the cid alias machinery) or peer death arriving while the
        frame waits its turn completes the request typed from the
        waiter's own progress tick.  Death also lands eagerly through
        the _fail_inflight failure listener; this RETRYING tick is the
        backstop (the one-shot listener may find the frame transiently
        owned — e.g. the RTS mid-send — and skip it) and the revoke
        path.  A poisoned rendezvous request also releases its parked
        descriptor (``rndv_id``): a park nobody will ever push must
        not pin the caller's buffers or stall the close quiesce."""
        state = self.ft_state
        if state is None:
            return

        def fail(exc) -> None:
            if rndv_id is not None:
                with self._rndv_lock:
                    if req._owned:
                        return  # CTS push started: transport owns it
                    self._pending_rndv.pop(rndv_id, None)
                    self._rndv_meta.pop(rndv_id, None)
                    self._rndv_trace.pop(rndv_id, None)
            req.complete_error(exc)

        def prog():
            if req.done or req._owned:
                # a worker is mid-send: its outcome (delivered, or a
                # transport error classified typed) is authoritative
                return
            if state.is_revoked(cid):
                fail(errors.Revoked(
                    f"isend on revoked cid={cid}", cid=cid))
            elif state.is_failed(dest):
                fail(errors.ProcFailed(
                    f"rank {dest} failed with an isend in flight "
                    f"(cause: {state.cause_of(dest)})",
                    failed_ranks=state.failed()))

        req._progress = prog

    def _fail_inflight(self, rank: int, cause: str) -> None:
        """Failure-listener hook (``FailureState.add_failure_listener``):
        a peer's death completes every parked isend toward it as typed
        ``ProcFailed`` — queued channel frames and parked rendezvous
        descriptors both — so waitall loops observe the failure instead
        of wedging on a corpse (the deferred twin of the blocking
        path's discovery-at-send classification)."""
        state = self.ft_state
        if state is None:
            return
        exc = errors.ProcFailed(
            f"rank {rank} failed with isends in flight (cause: {cause})",
            failed_ranks=state.failed(),
        )
        ch = self._out_channels.get(rank)
        if ch is not None:
            with ch.lock:
                # under ch.lock: an item is either still queued HERE
                # (error it — it will be skipped at pop) or already
                # popped-and-owned by a worker (its outcome is
                # authoritative); never both
                for _work, req, _finish in ch.queue:
                    if req is not None and not req._owned:
                        req.complete_error(exc)
        with self._rndv_lock:
            doomed = [(rid, meta[1])
                      for rid, meta in self._rndv_meta.items()
                      if meta[0] == rank
                      and (meta[1] is None or not meta[1]._owned)]
            for rid, _req in doomed:
                self._pending_rndv.pop(rid, None)
                self._rndv_meta.pop(rid, None)
                self._rndv_trace.pop(rid, None)
        for _rid, req in doomed:
            if req is not None:
                req.complete_error(exc)

    def _rndv_undelivered(self) -> bool:
        """Parked transfers still owed to the peers — the close-quiesce
        predicate.  Blocking-send parks (no request) are always owed;
        an isend park whose request already completed ERRORED (revoked
        or failed while parked — no CTS is ever coming) can never
        drain and must not stall the quiesce for the full timeout."""
        with self._rndv_lock:
            if not self._pending_rndv:
                return False
            for rid in self._pending_rndv:
                meta = self._rndv_meta.get(rid)
                if meta is None or meta[1] is None or not meta[1].done:
                    return True
            return False

    def _abandon_inflight(self, why: str) -> None:
        """Drain-or-abandon teardown of the in-flight set: complete
        every still-parked deferred send ERRORED (waiters unblock
        typed) and drop the parked descriptors (the hygiene gate's
        zero-orphan contract) — sever() abandons immediately (crash
        semantics), close() calls this only after its bounded quiesce
        gave every frame its chance to drain."""
        exc = errors.InternalError(why)
        for ch in list(self._out_channels.values()):
            with ch.lock:
                items = list(ch.queue)
                ch.queue.clear()
            for _work, req, _finish in items:
                if req is not None:
                    req.complete_error(exc)
        with self._rndv_lock:
            metas = list(self._rndv_meta.values())
            self._pending_rndv.clear()
            self._rndv_meta.clear()
            self._rndv_trace.clear()
        for _dest, req in metas:
            if req is not None:
                req.complete_error(exc)

    def _isend_eager(self, obj: Any, dest: int, tag: int, cid: int,
                     seq: int, dispatch, tctx=None):
        """Eager deferred send: pin the caller's buffers (pack_frames
        memoryview segments — zero copies) and queue the vectored
        sendmsg on the progress engine; the request completes when the
        kernel has the bytes."""
        from .requests import SendRequest

        header, oob = dss.pack_frames(
            *self._frame_objs(tag, cid, seq, obj, tctx),
            oob_min=int(mca_var.get("tcp_zero_copy_min", 0)),
        )
        segments = [header, *oob]
        req = SendRequest(pinned=segments, dispatch=dispatch)
        self._arm_isend_poison(req, dest, cid)
        self._inflight.add(req)
        spc.record("tcp_isend_deferred", 1)
        if oob:
            spc.record("tcp_zero_copy_sends", 1)
            spc.record("tcp_copy_bytes_avoided",
                       sum(v.nbytes for v in oob))

        def work():
            sock = self._endpoint(dest)
            self._framed_send(sock, segments)

        self._enqueue_deferred(dest, req, work, finish=True)
        return req

    def _isend_rndv(self, obj: Any, dest: int, tag: int, cid: int,
                    seq: int, nbytes: int, dispatch, tctx=None,
                    parent=None):
        """Rendezvous deferred send: the RTS parks only the DESCRIPTOR
        — the caller's buffers pinned by the request, no copy-at-park —
        and the receiver's CTS releases a push of those buffers
        directly over the data socket.  The request completes when the
        push has the bytes in the kernel (or errored, typed, when the
        peer dies / the cid is revoked while parked)."""
        from .requests import SendRequest

        req = SendRequest(dispatch=dispatch)
        self._inflight.add(req)
        spc.record("tcp_isend_deferred", 1)
        rndv_id, _oob = self._park_rndv(obj, dest, seq, req=req,
                                        tctx=tctx, parent=parent)
        self._arm_isend_poison(req, dest, cid, rndv_id=rndv_id)
        rts = dss.pack(
            *self._frame_objs(
                tag, cid, seq, (_RTS_MARK, self.rank, rndv_id, nbytes),
                tctx),
        )
        if parent is not None and ztrace.active:
            ztrace.instant(ztrace.RTS, self.rank, parent=parent,
                           dest=dest, tag=tag, cid=cid, seq=seq,
                           nbytes=nbytes)

        def send_rts():
            sock = self._endpoint(dest)
            self._framed_send(sock, rts)

        # the RTS rides the ordered channel (it IS the matchable
        # message — per-source FIFO with every eager frame before it);
        # its write does NOT complete the request — the data push does
        self._enqueue_deferred(dest, req, send_rts, finish=False)
        return req

    def _isend_sm(self, smtx: sm_mod.SmSender, obj: Any, dest: int,
                  tag: int, cid: int, seq: int, nbytes: int, dispatch,
                  tctx=None):
        """Shared-memory deferred send.  Ring backpressure already IS
        the in-flight bound, so a small frame tries the single-slot
        copy-in NONBLOCKING and is born complete when it lands; a full
        ring parks a producer continuation on the progress engine
        instead of blocking the caller (today's behavior), and larger
        frames take the fragment pipeline there too (the worker's
        copy-in overlaps the caller's compute — the same deferred
        contract, one transport over)."""
        from .requests import SendRequest

        req = SendRequest(dispatch=dispatch)
        self._arm_isend_poison(req, dest, cid)
        ch = self._out_channels.get(dest)
        idle = ch is None or not ch.busy()
        oob_min = int(mca_var.get("tcp_zero_copy_min", 0))
        frame_objs = self._frame_objs(tag, cid, seq, obj, tctx)
        if idle and nbytes + 512 <= min(smtx.slot_bytes, 32 << 10):
            try:
                wire = smtx.send_direct(
                    frame_objs, oob_min,
                    time.monotonic(), None,
                )
            except sm_mod.RingFull:
                pass  # park the continuation below
            except (errors.MpiError, OSError) as e:
                req.complete_error(self._deferred_exc(e, dest))
                return req
            else:
                if wire is not None:
                    spc.record("sm_bytes_sent", wire)
                    spc.record("sm_eager_sends", 1)
                    req.complete()
                    return req
                # frame does not fit one slot: fragment pipeline below
        prebuilt = None
        if idle:
            # larger frame, ring currently has room for ALL of it: run
            # the fragment pipeline inline — the copy-in never waits on
            # the consumer, so this is still nonblocking, and it skips
            # a worker handoff whose scheduling quantum costs more than
            # the copy on small hosts (measured on the han pipeline)
            prebuilt = dss.pack_frames(*frame_objs, oob_min=oob_min)
            try:
                done = smtx.try_send_frame(*prebuilt)
            except (errors.MpiError, OSError) as e:
                req.complete_error(self._deferred_exc(e, dest))
                return req
            if done is not None:
                wire, nfrags = done
                spc.record("sm_bytes_sent", wire)
                spc.record("sm_eager_sends" if nfrags == 1
                           else "sm_frag_sends", 1)
                req.complete()
                return req
        self._inflight.add(req)
        spc.record("tcp_isend_deferred", 1)

        def work():
            if prebuilt is not None:
                # the nonblocking attempt already serialized the frame:
                # stream the SAME header/segments once the ring drains
                # (re-serializing on the backpressured path would pay
                # the DSS pack twice for exactly the largest payloads)
                self._sm_send_prebuilt(smtx, dest, *prebuilt)
            else:
                # frame_objs already accounted its wire-context bytes:
                # hand the built header values through, not tctx
                self._sm_send(smtx, obj, dest, tag, cid, seq, nbytes,
                              objs=frame_objs)

        self._enqueue_deferred(dest, req, work, finish=True)
        return req

    def _sm_send_prebuilt(self, smtx: sm_mod.SmSender, dest: int,
                          header, oob) -> None:
        """Parked-continuation body for an sm isend whose frame was
        already serialized for the nonblocking attempt: the blocking
        fragment pipeline over the same pinned segments, with the
        `_sm_send` abort contract (peer death / local close classify
        out of the ring-full spin)."""
        state = self.ft_state
        closed = self._closed

        def abort():
            if closed.is_set():
                raise errors.InternalError(
                    f"sm send to rank {dest} on a closed proc"
                )
            if state is not None and state.is_failed(dest):
                raise errors.ProcFailed(
                    f"rank {dest} failed during an sm ring send",
                    failed_ranks=state.failed(),
                )

        deadline = time.monotonic() + self._timeout
        wire, nfrags = smtx.send_frame(header, oob, deadline, abort)
        spc.record("sm_bytes_sent", wire)
        spc.record("sm_eager_sends" if nfrags == 1 else "sm_frag_sends",
                   1)

    def isend(self, obj: Any, dest: int, tag: int = 0, cid: int = 0,
              poll: bool = False):
        """True MPI_Isend: the buffer-reuse contract is DEFERRED to
        request completion.  The caller's buffers are pinned (no eager
        copy, no rendezvous park copy) and handed to the per-proc
        progress engine — per-destination FIFO channels drained by the
        push-pool workers (eager: queued sendmsg; rendezvous: RTS parks
        the descriptor, CTS pushes the pinned buffers over the data
        socket; sm: slot copy-in, or a parked producer continuation
        when the ring is full).  ``wait()``/``test()`` gate buffer
        reuse and surface typed failures at completion: a revoked cid
        or known-failed destination returns an ERRORED request (never a
        synchronous raise), an in-flight send whose peer dies completes
        as ``ProcFailed``, a revoke flood poisons parked sends through
        the cid alias machinery.  ``poll=True`` marks a
        framework-internal send: errors raise raw at wait, bypassing
        the errhandler disposition."""
        from .requests import SendRequest

        if not 0 <= dest < self.size:
            raise errors.RankError(f"rank {dest} out of range")
        if tag < 0:
            raise errors.TagError(f"negative tag {tag}")
        if flightrec.active and not poll:
            flightrec.record(flightrec.SEND, rank=self.rank, dest=dest,
                             tag=tag, cid=cid, nb=True)
        dispatch = None if poll else self.call_errhandler
        state = self.ft_state
        if state is not None and state.is_revoked(cid):
            return SendRequest.errored(
                errors.Revoked(f"isend on revoked cid={cid}", cid=cid),
                dispatch=dispatch,
            )
        if state is not None and state.is_failed(dest):
            return SendRequest.errored(
                errors.ProcFailed(
                    f"rank {dest} is known failed "
                    f"(cause: {state.cause_of(dest)})",
                    failed_ranks=state.failed(),
                ),
                dispatch=dispatch,
            )
        seq = next(self._seq)
        # tracing plane (armed only): the deferred send span is an
        # instant at dispatch — the rendezvous push/deliver legs carry
        # the durations — and its context rides every frame below
        tspan = tctx = None
        if ztrace.active and not poll:
            tspan = ztrace.begin(ztrace.SEND, self.rank, dest=dest,
                                 tag=tag, cid=cid, seq=seq, nb=True)
            tctx = ztrace.wire_context(tspan.sid, seq)
            if tctx is None:
                tspan = None  # a disarm raced begin(): send untraced
        if dest == self.rank:
            # loopback (btl/self): the single defensive copy IS
            # completion — born complete, exactly like the blocking path
            nbytes = _payload_size(obj)
            try:
                payload = _loopback_copy(obj)
                spc.record("tcp_loopback_fast_deliveries", 1)
                spc.record("tcp_copy_bytes_avoided", nbytes)
            except _LoopbackFallback:
                frame = dss.pack(self.rank, tag, cid, seq, obj)
                payload = dss.unpack(frame)[4]
            env = Envelope(self.rank, tag, cid, seq)
            with self._incoming_cv:
                self.engine.incoming(env, payload)
                self._incoming_cv.notify_all()
            if tspan is not None:
                ztrace.instant(ztrace.DELIVER, self.rank,
                               parent=tspan.sid, trace=tctx[0],
                               src=self.rank, tag=tag, cid=cid, seq=seq,
                               transport="self")
                tspan.end(transport="self")
            return SendRequest.completed()
        nbytes = _payload_size(obj)
        smtx = self._sm_tx(dest)
        if smtx is not None:
            req = self._isend_sm(smtx, obj, dest, tag, cid, seq,
                                 nbytes, dispatch, tctx=tctx)
            if tspan is not None:
                tspan.end(transport="sm")
            return req
        if dest in self._sm_declined:
            spc.record("sm_fallback_tcp_sends", 1)
        limit = int(mca_var.get("tcp_eager_limit", 1 << 20))
        if nbytes > limit:
            req = self._isend_rndv(obj, dest, tag, cid, seq, nbytes,
                                   dispatch, tctx=tctx,
                                   parent=tspan.sid if tspan is not None
                                   else None)
            if tspan is not None:
                tspan.end(transport="rndv")
            return req
        req = self._isend_eager(obj, dest, tag, cid, seq, dispatch,
                                tctx=tctx)
        if tspan is not None:
            tspan.end(transport="tcp")
        return req

    def irecv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
              cid: int = 0, poll: bool = False):
        """Nonblocking matched receive returning a Request.  On an ft
        proc the request is failure-aware: classification (revoked cid,
        named dead source, ANY_SOURCE pending semantics) completes it
        ERRORED — typed, from the waiter's progress tick, mirroring the
        SendRequest path — instead of surfacing only at the next
        blocking call; a message matched after classification re-enters
        the engine for a retry (the abandoned/re-inject contract).
        ``poll=True`` marks a framework-internal receive (the agreement
        protocol's frame waits): typed errors raise raw at wait/test,
        bypassing the errhandler disposition, so fault-tolerant
        protocols observe peer death regardless of the user's
        disposition."""
        from .requests import Request

        state = self.ft_state
        abandoned = [False]
        req = Request(dispatch=None if poll else self.call_errhandler) \
            if state is not None else Request()

        def finalize(env: Envelope, payload: Any) -> None:
            # runs while _incoming_cv is held (all engine entry points
            # in this class take it), so `abandoned` is consistent
            if abandoned[0]:
                self.engine.incoming(env, payload)
                return
            req.complete(payload, source=env.src, tag=env.tag)

        def on_match(env: Envelope, payload: Any) -> None:
            if self._resolve_rndv(env, payload, finalize):
                return
            finalize(env, payload)

        with self._incoming_cv:
            self.engine.post_recv(source, tag, cid, on_match)
        if state is not None:
            def prog():
                if req.done:
                    return
                exc = ulfm.classify_recv_failure(state, source, cid)
                if exc is None:
                    return
                with self._incoming_cv:
                    if req.done:
                        return
                    abandoned[0] = True
                req.complete_error(exc)

            req._progress = prog
        return req

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
             cid: int = 0, timeout: float | None = None,
             return_status: bool = False, poll: bool = False) -> Any:
        """Blocking matched receive.  On timeout the posted receive is
        abandoned and any message it steals afterwards is re-injected into
        the matching engine, so a retry can still find it (the matching
        engines have no cancel in their C ABI; re-injection gives the same
        liveness).

        Timeout disposition: a timeout dispatches through the endpoint's
        errhandler (FATAL aborts, RETURN raises the typed error) —
        UNLESS ``poll=True``, which marks a framework-internal polling
        receive whose timeout is an expected outcome, not an error: it
        raises ``InternalError`` directly so service loops keep their
        poll semantics regardless of the user's disposition."""
        timeout = self._timeout if timeout is None else timeout
        if flightrec.active and not poll:
            flightrec.record(flightrec.RECV, rank=self.rank, src=source,
                             tag=tag, cid=cid)
        # tracing plane: the recv span covers post → completion (its
        # start vs the deliver span's stamp is the late-sender /
        # late-receiver signal); an error/timeout path never ends it
        trecv = None
        if ztrace.active and not poll:
            trecv = ztrace.begin(ztrace.RECV, self.rank, src=source,
                                 tag=tag, cid=cid)
        result: list[Any] = []
        envs: list[Envelope] = []
        done = threading.Event()
        abandoned = [False]

        def finalize(env: Envelope, payload: Any) -> None:
            # always invoked while _incoming_cv is held (all engine entry
            # points in this class take it), so `abandoned` is consistent
            if abandoned[0]:
                self.engine.incoming(env, payload)
                return
            result.append(payload)
            envs.append(env)
            done.set()

        def on_match(env: Envelope, payload: Any) -> None:
            # a rendezvous RTS resolves asynchronously; `finalize` then
            # runs when the data lands (same abandoned/re-inject contract)
            if self._resolve_rndv(env, payload, finalize):
                return
            finalize(env, payload)

        state = self.ft_state
        if state is not None:
            # revocation poisons pending AND future receives
            fail_exc = ulfm.classify_recv_failure(state, source, cid)
            if isinstance(fail_exc, errors.Revoked):
                if poll:
                    raise fail_exc
                return self.call_errhandler(fail_exc)
        with self._incoming_cv:
            self.engine.post_recv(source, tag, cid, on_match)
        if state is None:
            completed = done.wait(timeout)
            fail_exc = None
        else:
            # sliced wait so peer death classifies promptly: a receive
            # blocked on a rank that dies mid-wait must surface typed
            # ProcFailed, not ride out the full stall timeout
            fail_exc = None
            wait_deadline = time.monotonic() + timeout
            while True:
                if done.wait(0.02):
                    break
                fail_exc = ulfm.classify_recv_failure(state, source, cid)
                if fail_exc is not None or time.monotonic() > wait_deadline:
                    break
            completed = done.is_set()
        if not completed:
            with self._incoming_cv:
                if not done.is_set():
                    abandoned[0] = True
            if not done.is_set():
                if fail_exc is not None:
                    if poll:
                        raise fail_exc
                    return self.call_errhandler(fail_exc)
                # diagnosis: is the message parked unexpected while our
                # posted recv failed to match it? (engine race forensics;
                # queue snapshots only exist on the Python engine, which
                # takes them under its own lock — drain threads keep
                # appending)
                hit = self.engine.probe(source, tag, cid)
                unexpected, posted = [], []
                rows = getattr(self.engine, "debug_rows", None)
                if rows is not None:
                    posted, unexpected = rows()
                # peer death / stall surfaces here as a recv timeout;
                # dispatch per the communicator's errhandler disposition
                # rather than a bare raise (round-4, VERDICT weak #4)
                exc = errors.InternalError(
                    f"tcp recv timeout (src={source}, tag={tag}, "
                    f"cid={cid}); probe={hit}; stats={self.engine.stats()}"
                    f"; unexpected={unexpected}; posted={posted}"
                )
                if poll:
                    raise exc  # expected poll outcome, not an error
                # FATAL raises JobAbort, RETURN raises exc; a user
                # handler's return value becomes the API result
                # (core/errhandler.py's error-recovery contract)
                return self.call_errhandler(exc)
        if trecv is not None:
            trecv.end(src=envs[0].src, tag=envs[0].tag)
        if return_status:
            from .requests import Status, _payload_bytes

            env = envs[0]
            return result[0], Status(
                source=env.src, tag=env.tag,
                count_bytes=_payload_bytes(result[0]),
            )
        return result[0]

    def probe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG,
              cid: int = 0):
        return self.engine.probe(source, tag, cid)

    def sendrecv(self, obj: Any, dest: int, source: int = ANY_SOURCE,
                 sendtag: int = 0, recvtag: int = ANY_TAG, cid: int = 0):
        self.send(obj, dest, sendtag, cid)
        return self.recv(source, recvtag, cid)

    def barrier(self) -> None:
        """Dissemination barrier over the wire (two-level over the
        locality groups when the han layer is selected — the same
        dispatch seam the host collectives run through)."""
        from ..coll import host as coll_host

        han = coll_host._han_route(self, "barrier")
        if han is not None:
            return han.barrier(self)
        n = self.size
        k = 1
        while k < n:
            self.send(b"", (self.rank + k) % n, tag=0x7FFD, cid=0x7FFD)
            self.recv(source=(self.rank - k) % n, tag=0x7FFD, cid=0x7FFD)
            k <<= 1

    def close(self) -> None:
        # Metrics final flush first, while the store and our state are
        # both fully alive: the stop() below publishes one last
        # snapshot (final=True) so a job shorter than one publish
        # interval is still fleet-visible, then joins the publisher —
        # the zero-leaked-publisher-threads gate.
        if self._metrics_pub is not None:
            self._metrics_pub.stop()
            self._metrics_pub = None
        # Control floods next: an in-flight agreement announce or
        # revoke notice must reach the peers before the wire comes
        # down — the flood threads are fire-and-forget for their
        # CALLERS, but a CLOSING rank that takes its announce to the
        # grave strands survivors waiting to adopt it (observed as a
        # re-elected round computing a divergent agreement).  Bounded:
        # each flood's per-peer connect deadline is 1 s, and a wedged
        # flood must not hang shutdown.
        flood_deadline = time.monotonic() + 5.0
        with self._flood_lock:
            floods = list(self._flood_threads)
        for t in floods:
            while True:
                try:
                    t.join(max(0.0, flood_deadline - time.monotonic()))
                    break
                except RuntimeError:
                    # registered but not yet started (the flood's
                    # spawner is between append and start()): joining
                    # an unstarted thread raises — wait it into
                    # existence, bounded by the same deadline
                    if time.monotonic() >= flood_deadline:
                        break
                    time.sleep(0.001)
        # Quiesce the deferred-send channels and outstanding rendezvous
        # sends next — with the detector still beating: queued isend
        # frames and parked payloads exist only here until the workers
        # (or the receiver's CTS) move them, so tearing down immediately
        # after a buffered send() would destroy data the peer is
        # entitled to (ompi_mpi_finalize's quiesce-before-teardown
        # contract), and a long quiesce with our own beats already
        # silenced would get us falsely suspected by our observer.
        # Bounded wait: a peer that never matches cannot hang shutdown —
        # leftovers are abandoned ERRORED below, the same bounded-join
        # rule the control floods follow.
        if self.ft_state is not None:
            # re-sweep known-dead peers' in-flight sends before waiting
            # on them: a one-shot failure-listener sweep may have found
            # a frame transiently owned (RTS mid-send) and skipped it —
            # without a waiter ticking the poison, the park would only
            # fall to the bounded timeout below
            for r in self.ft_state.failed():
                self._fail_inflight(int(r), "known failed at close")
        deadline = time.monotonic() + self._timeout
        while time.monotonic() < deadline and any(
                ch.busy() for ch in list(self._out_channels.values())):
            time.sleep(0.005)
        while self._rndv_undelivered() and time.monotonic() < deadline:
            time.sleep(0.005)
        if self.ft_state is not None and not self._ft_dead:
            # the goodbye rides TCP while data may still sit in peers'
            # rings: wait (bounded) for our outbound rings to drain so
            # the BYE cannot overtake delivered-but-unread ring frames
            # — the per-socket-FIFO ordering argument, restored across
            # the transport split
            self._sm_quiesce(min(deadline, time.monotonic() + 5.0))
        if self.ft_state is not None and not self._ft_dead:
            # orderly departure: tell the survivors we are LEAVING, so
            # their detectors reconfigure the ring instead of suspecting
            # us via missed beats (cause="goodbye", pre-acknowledged:
            # never a detector false positive, and never a pending gate
            # on survivors' wildcard receives — finalize skew is not a
            # crash) — the goodbye the crash paths (sever/mute)
            # deliberately omit.  Per-socket FIFO puts the goodbye after
            # every frame already sent, so no delivered message is
            # reclassified as lost.
            goodbye = dss.pack(self.rank, 0, ulfm.FT_BYE_CID, 0,
                               [self.rank])
            # sm peers get the goodbye THROUGH their ring: it then
            # trails every data frame this direction ever produced
            # (exact per-direction FIFO — the same argument per-socket
            # ordering makes below), and it reaches peers the data
            # plane never warmed a TCP connection for
            ring_done: set[int] = set()
            with self._sm_lock:
                ring_peers = [(r, s) for r, s in self._sm_senders.items()
                              if s is not None]
            for r, smtx in ring_peers:
                if self.ft_state.is_failed(r):
                    continue
                try:
                    smtx.send_frame(goodbye, [],
                                    time.monotonic() + 2.0, None)
                    ring_done.add(r)
                except errors.MpiError:
                    pass  # wedged/stopped ring: fall through to TCP
            # remaining peers: only ALREADY-CONNECTED ones get the
            # goodbye directly — they are the ones holding delivered
            # frames the notice must trail, and our observer is among
            # them by construction (we beat toward it over a cached
            # socket).  Dialing fresh connections just to say goodbye
            # would stall shutdown on refused-connect retries for peers
            # already gone; recipients gossip the BYE onward
            # (_ft_ctrl), so never-connected survivors still learn.
            with self._conn_lock:
                connected = list(self._conns.items())
            for r, sock in connected:
                if not isinstance(r, int) or r in ring_done \
                        or r == self.rank or self.ft_state.is_failed(r):
                    # tuple keys are intercomm-bridge peers: a DIFFERENT
                    # job's rank namespace, where our departing rank
                    # number would poison their unrelated local rank
                    continue
                try:
                    self._framed_send(sock, goodbye)
                except OSError:
                    pass  # peer already gone: nothing to notify
        # the heartbeat thread stops only NOW: the goodbye above already
        # reconfigured the peers' rings, and our beats had to stay alive
        # through the quiesce so nobody suspected us mid-shutdown.  It
        # must still stop before teardown (no emitting into dying
        # sockets; fixtures assert no detector thread leaks).
        if self._detector is not None:
            self._detector.stop()
        self._closed.set()
        # shutdown() first, close() only after the reader threads exit:
        # drain/accept threads are blocked in recv/accept on these
        # sockets, and closing a socket another thread is reading frees
        # the fd number while that thread may still be about to read it —
        # a NEW socket reusing the fd then has its bytes STOLEN by the
        # old drain thread (rare, load-dependent message loss observed as
        # tcp recv timeouts under full-suite pressure).  shutdown
        # delivers EOF on the still-valid fd; the join guarantees nobody
        # is parked on the fd when it is finally freed.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns.values()) + self._dup_conns
            self._conns.clear()
            self._dup_conns = []
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        deadline = time.monotonic() + 5.0
        # the channel engine's close() joins the ONE reader thread that
        # replaced the accept thread + per-connection drains: after it
        # returns, nobody is parked on any of the fds freed below (the
        # fd-reuse byte-stealing hazard the old drain ladder documented)
        if self._chan_engine is not None:
            self._chan_engine.close(max(0.0, deadline - time.monotonic()))
        # the rendezvous-push pool drains with the proc: the quiesce loop
        # above already waited out pending transfers, so workers are idle
        # (or wedged on a dead peer, bounded by the join deadline) — the
        # conftest leak gate asserts none survive
        self._push_pool.close(max(0.0, deadline - time.monotonic()))
        # whatever the bounded quiesce could not deliver is abandoned
        # ERRORED now: no SendRequest may stay incomplete and no parked
        # descriptor may survive a closed proc (the hygiene gate's
        # zero-leak contract; an orderly close with live peers finds
        # nothing here)
        self._abandon_inflight(
            "proc closed with undeliverable sends in flight")
        # sm plane last: poll thread joined, peer mappings unmapped, own
        # segment unlinked — the lifecycle contract the hygiene gate
        # asserts (rings live exactly as long as their proc)
        self._sm_teardown()
        # han tag-window registrations die with the proc (the group-view
        # hygiene gate asserts a closed endpoint holds none)
        from . import groups as groups_mod

        groups_mod.release(self)
        try:
            self._listener.close()
        except OSError:
            pass
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
