"""mpisync — cross-rank clock offset estimation for trace alignment.

Re-design of ``ompi/tools/mpisync`` (SURVEY.md §2.6): the reference
measures per-node clock offsets against rank 0 so that tool timestamps
(PERUSE events, monitoring dumps) from different nodes can be merged on
one timeline.  Same algorithm here: for each rank, rank 0 runs a burst of
ping-pong exchanges, the offset estimate is ``theta = t_peer − (t0_send +
rtt/2)`` from the minimum-RTT sample (the classic Cristian/NTP estimator
the reference uses — its README cites the same approach).

Thread-ranks share one clock, so the *measured* offset is ~0; tests
inject synthetic skew through the ``clock`` hook — which is also how a
multi-host transport would plug real per-host clocks in.
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from ..pt2pt.universe import LocalUniverse

_SYNC_TAG = 0x51C
_SYNC_CID = 0x51C


def sync_clocks(uni: LocalUniverse, rounds: int = 16,
                clock: Callable[[int], float] | None = None
                ) -> list[float]:
    """Estimated clock offset of every rank relative to rank 0 (seconds).

    `clock(rank)` returns that rank's notion of "now" (defaults to the
    shared monotonic clock)."""
    if clock is None:
        clock = lambda rank: time.monotonic()  # noqa: E731

    def main(ctx):
        if ctx.rank == 0:
            offsets = [0.0]
            for peer in range(1, ctx.size):
                best_rtt = np.inf
                best_theta = 0.0
                for _ in range(rounds):
                    t0 = clock(0)
                    ctx.send(t0, dest=peer, tag=_SYNC_TAG, cid=_SYNC_CID)
                    t_peer = ctx.recv(
                        source=peer, tag=_SYNC_TAG, cid=_SYNC_CID
                    )
                    t1 = clock(0)
                    rtt = t1 - t0
                    if rtt < best_rtt:
                        best_rtt = rtt
                        best_theta = t_peer - (t0 + rtt / 2.0)
                offsets.append(best_theta)
            # done: release the peers
            for peer in range(1, ctx.size):
                ctx.send(None, dest=peer, tag=_SYNC_TAG + 1, cid=_SYNC_CID)
            return offsets
        while True:
            # serve ping-pongs until released
            probe_done = ctx.probe(source=0, tag=_SYNC_TAG + 1, cid=_SYNC_CID)
            if probe_done is not None:
                ctx.recv(source=0, tag=_SYNC_TAG + 1, cid=_SYNC_CID)
                return None
            probe = ctx.probe(source=0, tag=_SYNC_TAG, cid=_SYNC_CID)
            if probe is not None:
                ctx.recv(source=0, tag=_SYNC_TAG, cid=_SYNC_CID)
                ctx.send(
                    clock(ctx.rank), dest=0, tag=_SYNC_TAG, cid=_SYNC_CID
                )
            # zlint: disable=ZL003 -- ping-pong server: any real sleep here inflates the RTT the clock sync measures
            time.sleep(0)

    results = uni.run(main)
    return results[0]


def main(argv: list[str] | None = None) -> int:  # pragma: no cover - CLI
    import argparse

    p = argparse.ArgumentParser(description="clock-sync demo (mpisync analog)")
    p.add_argument("-n", "--ranks", type=int, default=4)
    p.add_argument("--skew", type=float, nargs="*", default=None,
                   help="per-rank synthetic skew seconds")
    args = p.parse_args(argv)
    uni = LocalUniverse(args.ranks)
    skew = args.skew or [0.0] * args.ranks
    offsets = sync_clocks(
        uni, clock=lambda r: time.monotonic() + skew[r]
    )
    for r, off in enumerate(offsets):
        print(f"rank {r}: offset {off * 1e6:+.1f} us")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
